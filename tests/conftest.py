"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behaviour is tested without TPU hardware via XLA's host
platform device count — the JAX idiom for "multi-node without a
cluster". Must run before jax is imported anywhere.
"""

import os

# Hard-set (not setdefault): the environment may pin JAX_PLATFORMS to a
# real accelerator platform; tests always run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# The environment may pre-import jax at interpreter startup (an
# accelerator-registration sitecustomize hook), in which case jax.config
# has already captured the original env. Override via the config API —
# this must happen before the first backend init, which conftest
# guarantees by running before any test imports.
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest


@pytest.fixture()
def store():
    from learningorchestra_tpu.core.store import InMemoryStore

    return InMemoryStore()


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


TITANIC_LIKE_CSV = """PassengerId,Survived,Pclass,Name,Sex,Age,SibSp,Parch,Fare,Embarked
1,0,3,"Braund, Mr. Owen",male,22,1,0,7.25,S
2,1,1,"Cumings, Mrs. John",female,38,1,0,71.2833,C
3,1,3,"Heikkinen, Miss. Laina",female,26,0,0,7.925,S
4,1,1,"Futrelle, Mrs. Jacques",female,35,1,0,53.1,S
5,0,3,"Allen, Mr. William",male,35,0,0,8.05,S
6,0,3,"Moran, Mr. James",male,,0,0,8.4583,Q
7,0,1,"McCarthy, Mr. Timothy",male,54,0,0,51.8625,S
8,0,3,"Palsson, Master. Gosta",male,2,3,1,21.075,S
"""


@pytest.fixture()
def titanic_csv(tmp_path):
    path = tmp_path / "titanic.csv"
    path.write_text(TITANIC_LIKE_CSV)
    return str(path)
