"""Unit tests for the typed column engine (core/columns.py) — the cell
semantics the store's block layer is built on: kind inference, int/float
round-trip fidelity, null vs missing distinction, copy-on-write
snapshots, appends with kind promotion, and all three serializations
(wire parts, WAL JSON records, numpy hand-off)."""

import numpy as np
import pytest

from learningorchestra_tpu.core.columns import MISSING, Column, merge_kind


class TestKinds:
    def test_int_column_roundtrips_ints(self):
        col = Column.from_values([1, 2, 3])
        assert col.kind == "i8"
        values = col.tolist()
        assert values == [1, 2, 3]
        assert all(type(v) is int for v in values)

    def test_float_column_roundtrips_floats(self):
        col = Column.from_values([1.5, 2.0])
        assert col.kind == "f8"
        values = col.tolist()
        assert values == [1.5, 2.0]
        assert all(type(v) is float for v in values)

    def test_mixed_int_float_preserves_each(self):
        # the dtype converter's int-collapse contract: "28" → 28, "2.5" → 2.5
        col = Column.from_values([28, 2.5, 7])
        assert col.kind == "num"
        values = col.tolist()
        assert values == [28, 2.5, 7]
        assert type(values[0]) is int and type(values[1]) is float

    def test_string_column(self):
        col = Column.from_values(["a", "bb", ""])
        assert col.kind == "str"
        assert col.tolist() == ["a", "bb", ""]

    def test_unicode_strings(self):
        values = ["héllo", "wörld", "日本", ""]
        col = Column.from_values(values)
        assert col.tolist() == values
        assert col.get(2) == "日本"

    def test_bool_column_stays_bool(self):
        col = Column.from_values([True, False, True])
        assert col.kind == "bool"
        values = col.tolist()
        assert values == [True, False, True]
        assert all(type(v) is bool for v in values)

    def test_mixed_bool_int_falls_to_obj(self):
        col = Column.from_values([True, 1])
        assert col.kind == "obj"
        assert col.tolist() == [True, 1]

    def test_none_tracked_in_mask(self):
        col = Column.from_values([1.0, None, 3.0])
        assert col.kind == "f8"
        assert col.tolist() == [1.0, None, 3.0]
        assert col.get(1) is None

    def test_missing_distinct_from_none(self):
        col = Column.from_values([1, MISSING, None])
        assert col.get(1) is MISSING
        assert col.get(2) is None
        assert col.tolist(pad_as_none=True) == [1, None, None]
        assert col.tolist(pad_as_none=False) == [1, MISSING, None]

    def test_nan_reads_as_none(self):
        col = Column.from_values([1.0, float("nan")])
        assert col.tolist() == [1.0, None]

    def test_huge_int_falls_back_to_obj(self):
        big = 2**100
        col = Column.from_values([1, big])
        assert col.tolist() == [1, big]

    def test_obj_kind_for_lists(self):
        col = Column.from_values([[0.1, 0.9], [0.8, 0.2]])
        assert col.kind == "obj"
        assert col.tolist() == [[0.1, 0.9], [0.8, 0.2]]


class TestAppend:
    def test_same_kind_append(self):
        col = Column.from_values([1, 2])
        col = col.append_column(Column.from_values([3, 4]))
        assert col.tolist() == [1, 2, 3, 4]

    def test_int_then_float_promotes_to_num(self):
        col = Column.from_values([1, 2])
        col = col.append_column(Column.from_values([2.5]))
        assert col.kind == "num"
        assert col.tolist() == [1, 2, 2.5]

    def test_str_then_int_promotes_to_obj(self):
        col = Column.from_values(["a"])
        col = col.append_column(Column.from_values([7]))
        assert col.kind == "obj"
        assert col.tolist() == ["a", 7]

    def test_pads_then_values_adopts_kind(self):
        col = Column.pads(2)
        col = col.append_column(Column.from_values([5, 6]))
        assert col.tolist(pad_as_none=False) == [MISSING, MISSING, 5, 6]
        assert col.get(3) == 6

    def test_values_then_pads(self):
        col = Column.from_values(["x", "y"])
        col = col.append_pads(2)
        assert col.tolist(pad_as_none=False) == ["x", "y", MISSING, MISSING]

    def test_many_appends_amortized(self):
        col = Column.from_values([0.0])
        for i in range(1, 300):
            col = col.append_column(Column.from_values([float(i)]))
        assert col.size == 300
        assert col.get(299) == 299.0

    def test_append_strings_grows_buffers(self):
        col = Column.from_values(["ab"])
        for i in range(100):
            col = col.append_column(Column.from_values([f"s{i}"]))
        assert col.get(100) == "s99"
        assert col.size == 101


class TestSet:
    def test_set_same_kind_in_place(self):
        col = Column.from_values([1, 2, 3])
        col = col.set(1, 9)
        assert col.tolist() == [1, 9, 3]

    def test_set_float_into_int_promotes(self):
        col = Column.from_values([1, 2])
        col = col.set(0, 0.5)
        assert col.kind == "num"
        assert col.tolist() == [0.5, 2]
        assert type(col.tolist()[1]) is int

    def test_set_string_cell_via_edits(self):
        col = Column.from_values(["a", "b", "c"])
        col = col.set(1, "a-much-longer-value")
        assert col.tolist() == ["a", "a-much-longer-value", "c"]
        assert col.get(1) == "a-much-longer-value"

    def test_set_none_and_back(self):
        col = Column.from_values([1, 2])
        col = col.set(0, None)
        assert col.get(0) is None
        col = col.set(0, 7)
        assert col.get(0) == 7

    def test_set_str_into_float_promotes_to_obj(self):
        col = Column.from_values([1.0, 2.0])
        col = col.set(1, "oops")
        assert col.kind == "obj"
        assert col.tolist() == [1.0, "oops"]

    def test_set_nan_reads_none(self):
        col = Column.from_values([1.0, 2.0])
        col = col.set(0, float("nan"))
        assert col.get(0) is None


class TestSnapshot:
    def test_snapshot_isolated_from_set(self):
        col = Column.from_values([1, 2, 3])
        snap = col.snapshot()
        col = col.set(0, 99)
        assert snap.tolist() == [1, 2, 3]
        assert col.tolist() == [99, 2, 3]

    def test_snapshot_isolated_from_append(self):
        col = Column.from_values([1.0])
        snap = col.snapshot()
        for i in range(50):
            col = col.append_column(Column.from_values([float(i)]))
        assert snap.tolist() == [1.0]

    def test_snapshot_isolated_from_append_then_set(self):
        # append may swap buffers without clearing masks' shared state;
        # a later set must still not tear the snapshot
        col = Column.from_values([1.0, None])
        snap = col.snapshot()
        col = col.append_column(Column.from_values([3.0] * 100))
        col = col.set(0, None)
        col = col.set(1, 5.0)
        assert snap.tolist() == [1.0, None]

    def test_str_snapshot_isolated_from_edits(self):
        col = Column.from_values(["a", "b"])
        snap = col.snapshot()
        col = col.set(0, "zzz")
        assert snap.tolist() == ["a", "b"]


class TestSlice:
    def test_slice_values(self):
        col = Column.from_values([1, 2, 3, 4, 5])
        assert col.slice(1, 4).tolist() == [2, 3, 4]

    def test_slice_strings(self):
        col = Column.from_values(["aa", "bb", "cc"])
        part = col.slice(1, 3)
        assert part.tolist() == ["bb", "cc"]

    def test_slice_with_masks(self):
        col = Column.from_values([1.0, None, 3.0, None])
        assert col.slice(1, 4).tolist() == [None, 3.0, None]


class TestUniqueCounts:
    def _as_pairs(self, groups):
        return {
            (g["_id"] if not isinstance(g["_id"], bool) else ("b", g["_id"])): g["count"]
            for g in groups
        }

    def test_int_counts(self):
        col = Column.from_values([3, 1, 3, 3])
        pairs = self._as_pairs(col.unique_counts())
        assert pairs == {3: 3, 1: 1}

    def test_string_counts(self):
        col = Column.from_values(["a", "b", "a"])
        pairs = self._as_pairs(col.unique_counts())
        assert pairs == {"a": 2, "b": 1}

    def test_none_group(self):
        col = Column.from_values([1.0, None, None])
        pairs = self._as_pairs(col.unique_counts())
        assert pairs == {1.0: 1, None: 2}

    def test_bool_counts_stay_bool(self):
        col = Column.from_values([True, True, False])
        groups = col.unique_counts()
        keys = {type(g["_id"]) for g in groups}
        assert keys == {bool}

    def test_num_kind_keeps_int_keys(self):
        col = Column.from_values([28, 2.5, 28])
        pairs = col.unique_counts()
        by_key = {repr(g["_id"]): g["count"] for g in pairs}
        assert by_key == {"28": 2, "2.5": 1}

    def test_list_cells_keep_bool_vs_one_distinct(self):
        # [True] and [1] must group apart, mirroring the scalar
        # bool-vs-1 parity (advisor r4: the old key tagged the list,
        # not its elements)
        col = Column.from_values([[True], [1], [True]])
        groups = {repr(g["_id"]): g["count"] for g in col.unique_counts()}
        assert groups == {"[True]": 2, "[1]": 1}

    def test_nested_unhashable_cells_group_by_repr(self):
        col = Column.from_values([[{"a": 1}], [{"a": 1}], [{"b": 2}]])
        counts = sorted(g["count"] for g in col.unique_counts())
        assert counts == [1, 2]


class TestSerialization:
    @pytest.mark.parametrize(
        "values",
        [
            [1, 2, 3],
            [1.5, None, 2.5],
            ["a", "", "ccc", None],
            [True, False],
            [28, 2.5, None],
            [[1, 2], None, "x", 5],
            [1, MISSING, None],
        ],
    )
    def test_wire_roundtrip(self, values):
        col = Column.from_values(values)
        meta, buffers = col.wire_parts()
        back = Column.from_wire_parts(meta, buffers)
        assert back.tolist(pad_as_none=False) == Column.from_values(
            values
        ).tolist(pad_as_none=False)

    @pytest.mark.parametrize(
        "values",
        [[1, 2], [1.5, None], ["a", None, "b"], [28, 2.5], [True], [MISSING, 7]],
    )
    def test_json_record_roundtrip(self, values):
        col = Column.from_values(values)
        back = Column.from_json_record(col.to_json_record())
        assert back.tolist(pad_as_none=False) == col.tolist(pad_as_none=False)

    def test_json_record_is_jsonable(self):
        import json

        col = Column.from_values([1.5, None, 2.0])
        json.dumps(col.to_json_record())


class TestNumpyHandoff:
    def test_to_float64_with_nulls(self):
        col = Column.from_values([1, None, 3])
        arr = col.to_float64()
        assert arr[0] == 1.0 and np.isnan(arr[1]) and arr[2] == 3.0

    def test_from_numpy_float(self):
        col = Column.from_numpy(np.array([1.0, np.nan, 3.0]))
        assert col.tolist() == [1.0, None, 3.0]

    def test_from_numpy_int(self):
        col = Column.from_numpy(np.arange(5))
        assert col.kind == "i8"
        assert col.tolist() == [0, 1, 2, 3, 4]

    def test_to_object_strings(self):
        col = Column.from_values(["x", None, "y"])
        arr = col.to_object()
        assert arr.dtype == object
        assert list(arr) == ["x", None, "y"]

    def test_from_nul_joined(self):
        buffer = b"alpha\x00\x00gamma\x00"
        col = Column.from_nul_joined(buffer, 3)
        assert col.tolist() == ["alpha", "", "gamma"]

    def test_tolist_json_safe_types(self):
        import json

        col = Column.from_values([1, 2])
        json.dumps(col.tolist())
        col2 = Column.from_values([True])
        json.dumps(col2.tolist())


class TestReviewRegressions:
    def test_num_all_float_roundtrips_serialization(self):
        # a num column whose int-mask is all False must survive the
        # wire/WAL round trip (the mask ships even when all-False)
        col = Column.from_values([2.5, 3.5])
        col = col.set(0, 2.5)  # stays f8; force num via append
        col = Column.from_values([1, 2.5])
        col = col.set(0, 0.5)  # intm now all-False
        back = Column.from_json_record(col.to_json_record())
        assert back.tolist() == [0.5, 2.5]
        back2 = Column.from_wire_parts(*col.wire_parts())
        assert back2.tolist() == [0.5, 2.5]
        assert back.unique_counts()  # must not crash on intm access

    def test_num_unique_merges_equal_int_and_float(self):
        # 2 and 2.0 are ONE group (dict/Counter/Mongo semantics); key
        # type follows the first occurrence
        col = Column.from_values([2, 2.0, 2])
        groups = col.unique_counts()
        assert len(groups) == 1
        assert groups[0]["count"] == 3
        assert groups[0]["_id"] == 2 and type(groups[0]["_id"]) is int

    def test_num_unique_float_first_occurrence_keeps_float(self):
        col = Column.from_values([2.0, 2, 2.5])
        groups = {repr(g["_id"]): g["count"] for g in col.unique_counts()}
        assert groups == {"2.0": 2, "2.5": 1}


def test_merge_kind_lattice():
    assert merge_kind("i8", "f8") == "num"
    assert merge_kind("empty", "str") == "str"
    assert merge_kind("bool", "i8") == "obj"
    assert merge_kind("str", "str") == "str"
    assert merge_kind("num", "i8") == "num"


class TestVecKind:
    """Fixed-width float64 vector columns — the probability matrix the
    model builder persists per prediction collection (reference
    model_builder.py:232-247 boxes Spark's probability vector per row;
    vec keeps it as one (rows, width) buffer)."""

    def test_from_numpy_2d(self):
        m = np.arange(12, dtype=np.float64).reshape(6, 2)
        col = Column.from_numpy(m)
        assert col.kind == "vec"
        assert col.tolist() == m.tolist()
        assert col.get(2) == [4.0, 5.0]

    def test_append_same_width_stays_vec(self):
        m = np.ones((3, 2))
        col = Column.from_numpy(m).append_column(Column.from_numpy(m * 2))
        assert col.kind == "vec" and col.size == 6
        assert col.get(3) == [2.0, 2.0]

    def test_append_width_mismatch_demotes_to_obj(self):
        col = Column.from_numpy(np.ones((2, 2)))
        col = col.append_column(Column.from_numpy(np.ones((2, 3))))
        assert col.kind == "obj"
        assert col.get(2) == [1.0, 1.0, 1.0]

    def test_pads_then_vec_adopts_width(self):
        col = Column.pads(3).append_column(
            Column.from_numpy(np.arange(4.0).reshape(2, 2))
        )
        assert col.kind == "vec" and col.size == 5
        assert col.get(0) is MISSING
        assert col.get(3) == [0.0, 1.0]

    def test_vec_then_pads(self):
        col = Column.from_numpy(np.ones((2, 2))).append_pads(2)
        assert col.kind == "vec" and col.size == 4
        assert col.tolist() == [[1.0, 1.0], [1.0, 1.0], None, None]

    def test_wire_and_wal_roundtrip(self):
        m = np.random.default_rng(3).random((5, 4))
        col = Column.from_numpy(m).append_pads(1)
        back = Column.from_wire_parts(*col.wire_parts())
        assert back.kind == "vec"
        assert back.tolist() == col.tolist()
        back2 = Column.from_json_record(col.to_json_record())
        assert back2.tolist() == col.tolist()

    def test_unique_counts_groups_rows(self):
        col = Column.from_numpy(
            np.array([[1.0, 2.0], [1.0, 2.0], [3.0, 4.0]])
        )
        groups = {tuple(g["_id"]): g["count"] for g in col.unique_counts()}
        assert groups == {(1.0, 2.0): 2, (3.0, 4.0): 1}

    def test_point_set_scalar_demotes_to_obj(self):
        col = Column.from_numpy(np.ones((3, 2)))
        col = col.set(1, "oops")
        assert col.kind == "obj"
        assert col.get(0) == [1.0, 1.0] and col.get(1) == "oops"

    def test_slice_shares_buffers(self):
        m = np.arange(8.0).reshape(4, 2)
        sliced = Column.from_numpy(m).slice(1, 3)
        assert sliced.kind == "vec"
        assert sliced.tolist() == m[1:3].tolist()

    def test_snapshot_copy_on_write(self):
        col = Column.from_numpy(np.zeros((3, 2)))
        snap = col.snapshot()
        col.set(0, None)  # mutates masks only; data row nulls out
        assert snap.get(0) == [0.0, 0.0]
        assert col.get(0) is None

    def test_zero_row_width_mismatch_append_is_noop(self):
        col = Column.from_numpy(np.ones((3, 4)))
        col = col.append_column(Column.from_numpy(np.empty((0, 2))))
        assert col.kind == "vec" and col.size == 3

    def test_nan_rows_are_null_cells(self):
        m = np.array([[1.0, np.nan], [1.0, 2.0]])
        col = Column.from_numpy(m)
        assert col.tolist() == [None, [1.0, 2.0]]
        assert col.get(0) is None
        groups = col.unique_counts()
        assert {repr(g["_id"]): g["count"] for g in groups} == {
            "[1.0, 2.0]": 1,
            "None": 1,
        }
        import json

        json.dumps(groups)  # no NaN tokens escape

    def test_unique_counts_on_demoted_obj_lists(self):
        col = Column.from_numpy(np.ones((2, 2)))
        col = col.append_column(Column.from_numpy(np.ones((1, 3))))
        assert col.kind == "obj"
        groups = {repr(g["_id"]): g["count"] for g in col.unique_counts()}
        assert groups == {"[1.0, 1.0]": 2, "[1.0, 1.0, 1.0]": 1}


class TestSpill:
    """Out-of-core columns: payload moves to disk-backed mappings,
    appends stream to the file, mutations copy back to RAM — the
    store's Mongo-owns-disk analogue (VERDICT r4 missing #2)."""

    def test_numeric_spill_roundtrip_and_file_append(self, tmp_path):
        values = list(range(1000))
        col = Column.from_values(values)
        before = col.resident_nbytes()
        released = col.spill_to(str(tmp_path), "a")
        assert released > 0
        assert col.is_spilled()
        assert col.resident_nbytes() < before
        assert col.tolist() == values
        # appends land in the FILE, not RAM
        col = col.append_column(Column.from_values([5000, 5001]))
        assert col.is_spilled()
        assert col.tolist() == values + [5000, 5001]
        assert col.get(1001) == 5001

    def test_str_spill_roundtrip(self, tmp_path):
        values = ["alpha", "beta", None, "γämmä"] * 100
        col = Column.from_values(values)
        assert col.spill_to(str(tmp_path), "s") > 0
        assert col.tolist() == values
        col = col.append_column(Column.from_values(["tail"]))
        assert col.is_spilled()
        assert col.tolist() == values + ["tail"]

    def test_vec_spill_roundtrip(self, tmp_path):
        import numpy as np

        matrix = np.arange(24, dtype=np.float64).reshape(8, 3)
        col = Column.from_numpy(matrix)
        assert col.spill_to(str(tmp_path), "v") > 0
        assert col.tolist() == matrix.tolist()
        col = col.append_column(Column.from_numpy(matrix + 100))
        assert col.is_spilled()
        assert col.tolist()[8:] == (matrix + 100).tolist()

    def test_point_write_materializes_back_to_ram(self, tmp_path):
        col = Column.from_values([1.0, 2.0, 3.0])
        col.spill_to(str(tmp_path), "m")
        col = col.set(1, 9.5)
        assert not col.is_spilled()
        assert col.tolist() == [1.0, 9.5, 3.0]

    def test_snapshot_isolated_from_spilled_append(self, tmp_path):
        col = Column.from_values(list(range(100)))
        col.spill_to(str(tmp_path), "snap")
        view = col.snapshot()
        col = col.append_column(Column.from_values([777]))
        assert view.size == 100
        assert view.tolist() == list(range(100))
        assert col.tolist()[-1] == 777

    def test_kind_promotion_after_spill_materializes(self, tmp_path):
        col = Column.from_values(list(range(10)))
        col.spill_to(str(tmp_path), "p")
        col = col.append_column(Column.from_values(["now a string"]))
        assert col.tolist() == list(range(10)) + ["now a string"]
