"""Mesh + sharding over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learningorchestra_tpu.parallel import (
    DATA_AXIS,
    default_mesh,
    make_mesh,
    pad_rows,
    shard_rows,
)


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_default_mesh_axes():
    mesh = default_mesh()
    assert mesh.shape[DATA_AXIS] == 8
    assert mesh.shape["model"] == 1


def test_mesh_2d():
    mesh = make_mesh(data=4, model=2)
    assert mesh.shape == {"data": 4, "model": 2}


def test_mesh_bad_divisor():
    with pytest.raises(ValueError):
        make_mesh(data=8, model=3)


def test_pad_rows():
    padded, mask = pad_rows(np.arange(10).reshape(10, 1), 8)
    assert padded.shape == (16, 1)
    assert mask.sum() == 10
    assert not mask[10:].any()


def test_shard_rows_masked_reduction():
    mesh = default_mesh()
    x = np.arange(1, 11, dtype=np.float64).reshape(10, 1)
    dev_x, dev_mask = shard_rows(x, mesh)
    assert dev_x.shape == (16, 1)
    # A masked sum over sharded rows == host sum: XLA inserts the psum.
    total = jnp.sum(jnp.where(dev_mask[:, None], dev_x, 0.0))
    assert float(total) == x.sum()
    # Each device holds 2 rows of the padded 16.
    assert len(dev_x.addressable_shards) == 8
    assert dev_x.addressable_shards[0].data.shape == (2, 1)


def test_mesh_subset_of_devices():
    mesh = make_mesh(data=2, model=3)  # 6 of 8 devices
    assert mesh.shape == {"data": 2, "model": 3}
