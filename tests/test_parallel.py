"""Mesh + sharding over the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learningorchestra_tpu.parallel import (
    DATA_AXIS,
    default_mesh,
    make_mesh,
    pad_rows,
    shard_rows,
)


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_default_mesh_axes():
    mesh = default_mesh()
    assert mesh.shape[DATA_AXIS] == 8
    assert mesh.shape["model"] == 1


def test_mesh_2d():
    mesh = make_mesh(data=4, model=2)
    assert mesh.shape == {"data": 4, "model": 2}


def test_mesh_bad_divisor():
    with pytest.raises(ValueError):
        make_mesh(data=8, model=3)


def test_pad_rows():
    padded, mask = pad_rows(np.arange(10).reshape(10, 1), 8)
    assert padded.shape == (16, 1)
    assert mask.sum() == 10
    assert not mask[10:].any()


class TestShapeBuckets:
    """Quarter-octave padded-shape grid: nearby dataset sizes share one
    padded shape so XLA programs are reused instead of recompiled per
    row count (VERDICT r4 weak #1 — the 10M compile tax)."""

    def test_bucket_grid_values(self):
        from learningorchestra_tpu.parallel.sharding import bucket_rows

        assert bucket_rows(8) == 8
        assert bucket_rows(9) == 10          # 8 * 1.25
        assert bucket_rows(1000) == 1024     # 512 * 2
        assert bucket_rows(1024) == 1024     # exact powers stay put
        assert bucket_rows(1_000_000) == 1_048_576
        assert bucket_rows(10_000_000) == 10_485_760  # 2^23 * 1.25
        # worst-case waste stays under 25%
        for n in (7, 99, 891, 12345, 3_333_333):
            assert n <= bucket_rows(n) <= n * 1.25

    def test_sizes_in_one_bucket_share_padded_shape(self):
        from learningorchestra_tpu.parallel.sharding import padded_row_count

        shapes = {padded_row_count(n, 8) for n in range(920_000, 1_048_577, 7919)}
        assert shapes == {1_048_576}

    def test_padded_count_aligns_to_mesh_multiple(self):
        from learningorchestra_tpu.parallel.sharding import padded_row_count

        assert padded_row_count(10, 8) == 16
        assert padded_row_count(11, 8) == 16  # bucket 12 -> align 16
        assert padded_row_count(640, 3) == 642

    def test_host_row_range_matches_bucketed_shapes(self):
        # per-host feeding must land on the same padded global shape as
        # the single-host path, or multi-host programs recompile
        from learningorchestra_tpu.parallel.multihost import host_row_range
        from learningorchestra_tpu.parallel.sharding import padded_row_count

        mesh = default_mesh()
        n = 950_001
        start, stop = host_row_range(n, mesh)
        assert (start, stop) == (0, n)  # single process owns all rows
        x = np.zeros((n, 1), dtype=np.float32)
        dev_x, _ = shard_rows(x, mesh)
        assert dev_x.shape[0] == padded_row_count(n, 8) == 1_048_576


def test_shard_rows_masked_reduction():
    mesh = default_mesh()
    x = np.arange(1, 11, dtype=np.float64).reshape(10, 1)
    dev_x, dev_mask = shard_rows(x, mesh)
    assert dev_x.shape == (16, 1)
    # A masked sum over sharded rows == host sum: XLA inserts the psum.
    total = jnp.sum(jnp.where(dev_mask[:, None], dev_x, 0.0))
    assert float(total) == x.sum()
    # Each device holds 2 rows of the padded 16.
    assert len(dev_x.addressable_shards) == 8
    assert dev_x.addressable_shards[0].data.shape == (2, 1)


def test_mesh_subset_of_devices():
    mesh = make_mesh(data=2, model=3)  # 6 of 8 devices
    assert mesh.shape == {"data": 2, "model": 3}


class TestSegmentSteps:
    """Watchdog-safe program segmentation (ml/base.segment_steps):
    long iterative fits dispatch as several same-shaped programs so no
    single XLA execution runs for minutes on a watchdog-guarded chip."""

    def test_small_fits_stay_single_program(self):
        from learningorchestra_tpu.ml.base import segment_steps

        assert segment_steps(100, 1_000_000, 180e6) == 100
        assert segment_steps(20, 1_000_000, 40e6) == 20

    def test_large_fits_segment_to_divisors(self):
        from learningorchestra_tpu.ml.base import segment_steps

        # every segment the same static shape: result divides the total
        assert segment_steps(100, 10_000_000, 180e6) == 10
        assert segment_steps(20, 10_000_000, 40e6) == 4
        assert segment_steps(97, 10_000_000, 180e6) == 1  # prime total

    def test_feature_width_scales_cost(self):
        from learningorchestra_tpu.ml.base import segment_steps

        narrow = segment_steps(20, 1_000_000, 40e6, features=16)
        wide = segment_steps(20, 1_000_000, 40e6, features=64)
        assert narrow == 20 and wide == 10

    def test_budget_scale_knob_multiplies(self, monkeypatch):
        from learningorchestra_tpu.ml import base

        # LO_PROGRAM_ROW_STEPS is a MULTIPLIER on every budget (read
        # once at import into _PROGRAM_BUDGET_SCALE, so patch the
        # constant): 10x budget -> 10x longer segments
        assert base.segment_steps(100, 10_000_000, 180e6) == 10
        monkeypatch.setattr(base, "_PROGRAM_BUDGET_SCALE", 10.0)
        assert base.segment_steps(100, 10_000_000, 180e6) == 100

    def test_largest_divisor(self):
        from learningorchestra_tpu.ml.base import largest_divisor

        assert largest_divisor(20, 7) == 5
        assert largest_divisor(20, 20) == 20
        assert largest_divisor(20, 7, multiple_of=2) == 4
        assert largest_divisor(20, 1, multiple_of=2) == 2  # fallback
        assert largest_divisor(97, 50) == 1

    def test_zero_iteration_fits_return_initial_models(self):
        # MLlib allows maxIter=0 etc.; the segmented wrappers must keep
        # the old lax.scan(length=0) behavior instead of crashing
        from learningorchestra_tpu.ml.logistic import LogisticRegression
        from learningorchestra_tpu.ml.trees import GBTClassifier, RandomForestClassifier

        rng = np.random.default_rng(0)
        X = rng.normal(size=(32, 4))
        y = (X[:, 0] > 0).astype(np.int32)
        lr = LogisticRegression(max_iter=0).fit(X, y)
        assert np.asarray(lr.params["w"]).shape == (4, 2)
        gb = GBTClassifier(rounds=0).fit(X, y)
        assert gb.predict(X[:4]).shape == (4,)
        rf = RandomForestClassifier(num_trees=0, max_depth=2).fit(X, y)
        assert np.asarray(rf.features_heap).shape[0] == 0

    def test_segmented_lr_matches_single_program(self, monkeypatch):
        # 12 iterations in 3 segments == 12 in one program: the carried
        # optimizer state makes segmentation invisible to the result
        import jax.numpy as jnp

        from learningorchestra_tpu.ml import logistic

        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.int32)
        X_dev = jnp.asarray(X)
        y_dev = jnp.asarray(y)
        mask = jnp.ones(64, jnp.float32)
        params = {
            "w": jnp.zeros((4, 2), jnp.float32),
            "b": jnp.zeros((2,), jnp.float32),
        }
        single, _ = logistic._fit(params, X_dev, y_dev, mask, 12, jnp.float32(0.0))
        # budget that yields 4-iteration segments at 64 rows x 4 features
        monkeypatch.setattr(logistic, "_LR_ROW_ITERS_BUDGET", 64.0)
        from learningorchestra_tpu.ml.base import segment_steps

        assert segment_steps(12, 64, 64.0, features=4) == 4
        segmented, _ = logistic._fit(params, X_dev, y_dev, mask, 12, jnp.float32(0.0))
        np.testing.assert_allclose(
            np.asarray(single["w"]), np.asarray(segmented["w"]), rtol=1e-5
        )

    def test_lr_tol_stops_early_and_matches_quality(self):
        # MLlib-parity convergence: a converged fit stops before
        # max_iter (checked at segment granularity) with the same
        # decision quality as the full run
        import jax.numpy as jnp

        from learningorchestra_tpu.ml import logistic

        rng = np.random.default_rng(0)
        X = (rng.normal(size=(20_000, 8))).astype(np.float32)
        y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.int32)
        X_dev = jnp.asarray(X)
        y_dev = jnp.asarray(y)
        mask = jnp.ones(len(X), jnp.float32)
        params = {
            "w": jnp.zeros((8, 2), jnp.float32),
            "b": jnp.zeros((2,), jnp.float32),
        }
        p_stop, losses = logistic._fit(
            params, X_dev, y_dev, mask, 100, jnp.float32(0.0)
        )
        assert np.asarray(losses).shape[0] < 100  # converged early
        p_full, losses_full = logistic._fit(
            params, X_dev, y_dev, mask, 100, jnp.float32(0.0), tol=0.0
        )
        assert np.asarray(losses_full).shape[0] == 100
        pred_stop = np.argmax(np.asarray(X @ p_stop["w"] + p_stop["b"]), 1)
        pred_full = np.argmax(np.asarray(X @ p_full["w"] + p_full["b"]), 1)
        assert (pred_stop == pred_full).mean() > 0.999
