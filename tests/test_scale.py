"""Opt-in scale proof (VERDICT r3 missing #1: 10M+ rows end to end).

Skipped by default: the full-size run belongs on the TPU bench host
(``python scale.py``, ~10-20 min at 10M rows). Set ``LO_SCALE_TEST`` to
a row count to run the same path inside pytest at that size, e.g.::

    LO_SCALE_TEST=2000000 python -m pytest tests/test_scale.py -q

The assertion set is the "done" criterion from the round-3 review: the
dataset ingests, all five classifiers train and write predictions, and
peak memory stays within a small multiple of the bytes actually stored
(boxed-object storage failed this by an order of magnitude).
"""

import os

import pytest


@pytest.mark.skipif(
    not os.environ.get("LO_SCALE_TEST"),
    reason="set LO_SCALE_TEST=<rows> to run the scale proof",
)
def test_scale_end_to_end():
    import scale

    rows = int(os.environ["LO_SCALE_TEST"])
    out = scale.run_scale(rows, ["lr", "dt", "rf", "gb", "nb"])
    assert set(out["accuracy"]) == {"lr", "dt", "rf", "gb", "nb"}
    for name, accuracy in out["accuracy"].items():
        assert accuracy > 0.8, (name, accuracy)
    # typed blocks: memory tracks stored bytes, not boxed-object count
    assert out["rss_over_stored"] < 6, out
