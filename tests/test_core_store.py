"""Core store: Mongo-contract semantics, WAL durability, aggregation."""

import threading

import pytest

from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
    matches,
    parse_query,
)


def test_insert_find_ordering(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False})
    store.insert_many("ds", [{ROW_ID: i, "x": i * 10} for i in range(1, 6)])
    docs = list(store.find("ds"))
    assert [d[ROW_ID] for d in docs] == [0, 1, 2, 3, 4, 5]


def test_skip_limit_pagination(store):
    store.insert_many("ds", [{ROW_ID: i, "x": i} for i in range(10)])
    docs = list(store.find("ds", skip=3, limit=4))
    assert [d[ROW_ID] for d in docs] == [3, 4, 5, 6]


def test_query_subset_match(store):
    store.insert_many(
        "ds",
        [
            {ROW_ID: 1, "a": "x", "b": 1},
            {ROW_ID: 2, "a": "y", "b": 1},
            {ROW_ID: 3, "a": "x", "b": 2},
        ],
    )
    assert [d[ROW_ID] for d in store.find("ds", {"a": "x"})] == [1, 3]
    assert store.find_one("ds", {"a": "y"})[ROW_ID] == 2
    assert store.find_one("ds", {"a": "zzz"}) is None


def test_update_one_sets_fields(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
    store.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True, "fields": ["a"]})
    meta = store.metadata("ds")
    assert meta["finished"] is True and meta["fields"] == ["a"]
    assert store.is_finished("ds")


def test_duplicate_id_rejected(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_one("ds", {ROW_ID: 1})


def test_drop_and_list(store):
    store.insert_one("a", {ROW_ID: 1})
    store.insert_one("b", {ROW_ID: 1})
    assert sorted(store.list_collections()) == ["a", "b"]
    store.drop("a")
    assert store.list_collections() == ["b"]


def test_aggregate_group_count(store):
    # The histogram service's $group pushdown (reference: histogram.py:63-69).
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds"})
    store.insert_many(
        "ds", [{ROW_ID: i, "sex": "m" if i % 3 else "f"} for i in range(1, 10)]
    )
    result = store.aggregate(
        "ds", [{"$group": {"_id": "$sex", "count": {"$sum": 1}}}]
    )
    counts = {row["_id"]: row["count"] for row in result}
    assert counts == {"m": 6, "f": 3}


def test_read_columns_excludes_metadata(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "fields": ["x"]})
    store.insert_many("ds", [{ROW_ID: i, "x": i, "y": str(i)} for i in range(1, 4)])
    cols = store.read_columns("ds")
    assert cols["x"] == [1, 2, 3]
    assert cols["y"] == ["1", "2", "3"]


def test_wal_replay_roundtrip(tmp_path):
    data_dir = str(tmp_path / "wal")
    first = InMemoryStore(data_dir=data_dir)
    first.insert_one("ds", {ROW_ID: 0, "finished": False})
    first.insert_many("ds", [{ROW_ID: 1, "x": 1}, {ROW_ID: 2, "x": 2}])
    first.update_one("ds", {ROW_ID: 0}, {"finished": True})
    first.insert_one("gone", {ROW_ID: 1})
    first.drop("gone")

    reopened = InMemoryStore(data_dir=data_dir)
    assert reopened.list_collections() == ["ds"]
    assert reopened.metadata("ds")["finished"] is True
    assert reopened.count("ds") == 3

    reopened.compact()
    compacted = InMemoryStore(data_dir=data_dir)
    assert compacted.count("ds") == 3


def test_concurrent_inserts_thread_safe(store):
    def writer(start):
        store.insert_many("ds", [{ROW_ID: start + i} for i in range(100)])

    threads = [threading.Thread(target=writer, args=(i * 100,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert store.count("ds") == 800


def test_parse_query_accepts_json_and_python_repr():
    assert parse_query("{}") == {}
    assert parse_query('{"a": 1}') == {"a": 1}
    assert parse_query("{'a': 1}") == {"a": 1}  # reference client's str(dict)
    assert parse_query(None) == {}


def test_matches_subset():
    assert matches({"a": 1, "b": 2}, {"a": 1})
    assert not matches({"a": 1}, {"a": 2})
    assert not matches({"a": 1}, {"missing": 1})


def test_insert_many_atomic_on_duplicate(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_many("ds", [{ROW_ID: 5}, {ROW_ID: 1}])
    # nothing from the failed batch was applied
    assert [d[ROW_ID] for d in store.find("ds")] == [1]


def test_job_manager_rejects_active_duplicate_name():
    import time as _time

    from learningorchestra_tpu.core.jobs import JobManager

    jm = JobManager()
    jm.submit("j", _time.sleep, 0.3)
    with pytest.raises(ValueError):
        jm.submit("j", _time.sleep, 0.01)
    jm.wait("j", timeout=5)
    jm.submit("j", _time.sleep, 0.01)  # allowed after completion
    assert jm.wait("j", timeout=5).state == "finished"
