"""Core store: Mongo-contract semantics, WAL durability, aggregation."""

import json
import threading

import pytest

from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
    matches,
    parse_query,
)


def test_insert_find_ordering(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False})
    store.insert_many("ds", [{ROW_ID: i, "x": i * 10} for i in range(1, 6)])
    docs = list(store.find("ds"))
    assert [d[ROW_ID] for d in docs] == [0, 1, 2, 3, 4, 5]


def test_skip_limit_pagination(store):
    store.insert_many("ds", [{ROW_ID: i, "x": i} for i in range(10)])
    docs = list(store.find("ds", skip=3, limit=4))
    assert [d[ROW_ID] for d in docs] == [3, 4, 5, 6]


def test_query_subset_match(store):
    store.insert_many(
        "ds",
        [
            {ROW_ID: 1, "a": "x", "b": 1},
            {ROW_ID: 2, "a": "y", "b": 1},
            {ROW_ID: 3, "a": "x", "b": 2},
        ],
    )
    assert [d[ROW_ID] for d in store.find("ds", {"a": "x"})] == [1, 3]
    assert store.find_one("ds", {"a": "y"})[ROW_ID] == 2
    assert store.find_one("ds", {"a": "zzz"}) is None


def test_update_one_sets_fields(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
    store.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True, "fields": ["a"]})
    meta = store.metadata("ds")
    assert meta["finished"] is True and meta["fields"] == ["a"]
    assert store.is_finished("ds")


def test_duplicate_id_rejected(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_one("ds", {ROW_ID: 1})


def test_drop_and_list(store):
    store.insert_one("a", {ROW_ID: 1})
    store.insert_one("b", {ROW_ID: 1})
    assert sorted(store.list_collections()) == ["a", "b"]
    store.drop("a")
    assert store.list_collections() == ["b"]


def test_aggregate_group_count(store):
    # The histogram service's $group pushdown (reference: histogram.py:63-69).
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds"})
    store.insert_many(
        "ds", [{ROW_ID: i, "sex": "m" if i % 3 else "f"} for i in range(1, 10)]
    )
    result = store.aggregate(
        "ds", [{"$group": {"_id": "$sex", "count": {"$sum": 1}}}]
    )
    counts = {row["_id"]: row["count"] for row in result}
    assert counts == {"m": 6, "f": 3}


def test_read_columns_excludes_metadata(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "fields": ["x"]})
    store.insert_many("ds", [{ROW_ID: i, "x": i, "y": str(i)} for i in range(1, 4)])
    cols = store.read_columns("ds")
    assert cols["x"] == [1, 2, 3]
    assert cols["y"] == ["1", "2", "3"]


def test_wal_replay_roundtrip(tmp_path):
    data_dir = str(tmp_path / "wal")
    first = InMemoryStore(data_dir=data_dir)
    first.insert_one("ds", {ROW_ID: 0, "finished": False})
    first.insert_many("ds", [{ROW_ID: 1, "x": 1}, {ROW_ID: 2, "x": 2}])
    first.update_one("ds", {ROW_ID: 0}, {"finished": True})
    first.insert_one("gone", {ROW_ID: 1})
    first.drop("gone")

    reopened = InMemoryStore(data_dir=data_dir)
    assert reopened.list_collections() == ["ds"]
    assert reopened.metadata("ds")["finished"] is True
    assert reopened.count("ds") == 3

    reopened.compact()
    compacted = InMemoryStore(data_dir=data_dir)
    assert compacted.count("ds") == 3


def test_concurrent_inserts_thread_safe(store):
    def writer(start):
        store.insert_many("ds", [{ROW_ID: start + i} for i in range(100)])

    threads = [threading.Thread(target=writer, args=(i * 100,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert store.count("ds") == 800


def test_parse_query_accepts_json_and_python_repr():
    assert parse_query("{}") == {}
    assert parse_query('{"a": 1}') == {"a": 1}
    assert parse_query("{'a': 1}") == {"a": 1}  # reference client's str(dict)
    assert parse_query(None) == {}


def test_matches_subset():
    assert matches({"a": 1, "b": 2}, {"a": 1})
    assert not matches({"a": 1}, {"a": 2})
    assert not matches({"a": 1}, {"missing": 1})


def test_insert_many_atomic_on_duplicate(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_many("ds", [{ROW_ID: 5}, {ROW_ID: 1}])
    # nothing from the failed batch was applied
    assert [d[ROW_ID] for d in store.find("ds")] == [1]


def test_job_manager_rejects_active_duplicate_name():
    import time as _time

    from learningorchestra_tpu.core.jobs import JobManager

    jm = JobManager()
    jm.submit("j", _time.sleep, 0.3)
    with pytest.raises(ValueError):
        jm.submit("j", _time.sleep, 0.01)
    jm.wait("j", timeout=5)
    jm.submit("j", _time.sleep, 0.01)  # allowed after completion
    assert jm.wait("j", timeout=5).state == "finished"


def test_matches_query_operators():
    doc = {"a": 5, "s": "x"}
    assert matches(doc, {"a": {"$gt": 4}})
    assert not matches(doc, {"a": {"$gt": 5}})
    assert matches(doc, {"a": {"$gte": 5, "$lte": 5}})
    assert matches(doc, {"a": {"$lt": 6}})
    assert not matches(doc, {"a": {"$lt": 5}})
    assert matches(doc, {"a": {"$ne": 4}})
    assert not matches(doc, {"a": {"$ne": 5}})
    assert matches(doc, {"a": {"$eq": 5}})
    assert matches(doc, {"s": {"$in": ["x", "y"]}})
    assert not matches(doc, {"s": {"$nin": ["x", "y"]}})
    assert matches(doc, {"missing": {"$exists": False}})
    assert matches(doc, {"a": {"$exists": True}})
    assert not matches(doc, {"a": {"$exists": False}})
    # operator on a missing key never matches
    assert not matches(doc, {"missing": {"$gt": 0}})
    # incomparable types (None vs number) are a non-match, not an error
    assert not matches({"a": None}, {"a": {"$gt": 0}})
    # a non-operator dict value still means plain equality
    assert matches({"a": {"x": 1}}, {"a": {"x": 1}})


def test_find_with_operator_query(store):
    store.insert_many("ds", [{ROW_ID: i, "x": i} for i in range(1, 8)])
    assert [d[ROW_ID] for d in store.find("ds", {"x": {"$gte": 3, "$lt": 6}})] == [
        3,
        4,
        5,
    ]
    assert [d[ROW_ID] for d in store.find("ds", {"x": {"$in": [2, 7]}})] == [2, 7]


def test_create_collection_atomic_claim(store):
    assert store.create_collection("ds") is True
    assert store.create_collection("ds") is False
    assert "ds" in store.list_collections()
    # claimed collection accepts documents as usual
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
    assert store.metadata("ds")["finished"] is False


def test_create_collection_concurrent_single_winner(store):
    wins = []
    barrier = threading.Barrier(8)

    def claim():
        barrier.wait()
        if store.create_collection("target"):
            wins.append(1)

    threads = [threading.Thread(target=claim) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1


def test_wal_replays_created_empty_collection(tmp_path):
    data_dir = str(tmp_path / "wal")
    first = InMemoryStore(data_dir=data_dir)
    first.create_collection("claimed")
    first.insert_one("full", {ROW_ID: 1})
    first.compact()
    second = InMemoryStore(data_dir=data_dir)
    assert sorted(second.list_collections()) == ["claimed", "full"]


def test_matches_mongo_missing_field_and_logicals():
    from learningorchestra_tpu.core.store import UnsupportedQueryError

    # $ne / $nin match documents lacking the field (Mongo semantics)
    assert matches({"a": 1}, {"b": {"$ne": 5}})
    assert matches({"a": 1}, {"b": {"$nin": [5]}})
    assert not matches({"b": 5}, {"b": {"$ne": 5}})
    # $regex
    assert matches({"s": "hello"}, {"s": {"$regex": "ell"}})
    assert not matches({"s": "hello"}, {"s": {"$regex": "^x"}})
    assert not matches({"s": 5}, {"s": {"$regex": "5"}})
    # $not
    assert matches({"a": 1}, {"a": {"$not": {"$gt": 5}}})
    assert not matches({"a": 9}, {"a": {"$not": {"$gt": 5}}})
    # top-level logicals
    assert matches({"a": 1}, {"$or": [{"a": 1}, {"a": 2}]})
    assert not matches({"a": 3}, {"$or": [{"a": 1}, {"a": 2}]})
    assert matches({"a": 1, "b": 2}, {"$and": [{"a": 1}, {"b": 2}]})
    assert matches({"a": 3}, {"$nor": [{"a": 1}, {"a": 2}]})
    # unknown operators raise (REST maps to 400) instead of silent no-match
    with pytest.raises(UnsupportedQueryError):
        matches({"a": 1}, {"a": {"$mod": [2, 0]}})
    with pytest.raises(UnsupportedQueryError):
        matches({"a": 1}, {"$where": "1"})


def test_ingest_claim_shares_create_collection_gate(store, titanic_csv):
    from learningorchestra_tpu.core.ingest import write_ingest_metadata

    assert store.create_collection("claimed")
    with pytest.raises(KeyError):
        write_ingest_metadata(store, "claimed", titanic_csv)


class TestColumnarBlock:
    def test_insert_columns_roundtrip_find(self, store):
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
        store.insert_columns("ds", {"a": ["1", "2", "3"], "b": ["x", "y", "z"]})
        docs = list(store.find("ds"))
        assert [d[ROW_ID] for d in docs] == [0, 1, 2, 3]
        assert docs[1] == {"a": "1", "b": "x", ROW_ID: 1}
        assert docs[3] == {"a": "3", "b": "z", ROW_ID: 3}
        assert store.count("ds") == 4

    def test_insert_columns_appends_contiguously(self, store):
        store.insert_columns("ds", {"a": [1, 2]})
        store.insert_columns("ds", {"a": [3, 4]})  # start inferred = 3
        assert store.read_columns("ds", ["a", ROW_ID]) == {
            "a": [1, 2, 3, 4],
            ROW_ID: [1, 2, 3, 4],
        }
        with pytest.raises(ValueError):
            store.insert_columns("ds", {"a": [9]}, start_id=99)

    def test_group_keeps_bool_distinct_from_int(self, store):
        pipeline = [{"$group": {"_id": "$v", "count": {"$sum": 1}}}]
        # block fast path
        store.insert_columns("blk", {"v": [1, True, 1, False, 0]})
        groups = {
            (isinstance(g["_id"], bool), g["_id"]): g["count"]
            for g in store.aggregate("blk", pipeline)
        }
        assert groups == {
            (False, 1): 2, (True, True): 1, (True, False): 1, (False, 0): 1
        }
        # row path (overlay rows force _group_count)
        store.insert_one("rows", {"v": 1})
        store.insert_one("rows", {"v": True})
        row_groups = {
            (isinstance(g["_id"], bool), g["_id"]): g["count"]
            for g in store.aggregate("rows", pipeline)
        }
        assert row_groups == {(False, 1): 1, (True, True): 1}

    def test_read_columns_start_limit_block_path(self, store):
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
        store.insert_columns("ds", {"a": list(range(10, 20))})
        assert store.read_columns("ds", ["a", ROW_ID], start=2, limit=3) == {
            "a": [12, 13, 14],
            ROW_ID: [3, 4, 5],
        }
        # past-the-end start and oversize limit clamp, not raise
        assert store.read_columns("ds", ["a"], start=8, limit=99) == {
            "a": [18, 19]
        }
        assert store.read_columns("ds", ["a"], start=50, limit=5) == {"a": []}

    def test_read_columns_start_limit_row_path(self, store):
        # overlay rows force the row-merge fallback; same slicing contract
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
        store.insert_columns("ds", {"a": list(range(5))})
        store.insert_one("ds", {"a": 99})  # overlay append
        assert store.read_columns("ds", ["a"], start=3, limit=2) == {
            "a": [3, 4]
        }
        assert store.read_columns("ds", ["a"], start=5) == {"a": [99]}

    def test_insert_columns_ragged_rejected(self, store):
        with pytest.raises(ValueError):
            store.insert_columns("ds", {"a": [1], "b": [1, 2]})

    def test_insert_columns_overlay_collision(self, store):
        store.insert_one("ds", {ROW_ID: 2, "x": "row"})
        with pytest.raises(KeyError):
            store.insert_columns("ds", {"a": [1, 2, 3]}, start_id=1)

    def test_insert_one_into_block_range_rejected(self, store):
        store.insert_columns("ds", {"a": [1, 2, 3]})
        with pytest.raises(KeyError):
            store.insert_one("ds", {ROW_ID: 2, "a": 9})
        # append after the block auto-assigns the next id
        store.insert_one("ds", {"a": 4})
        assert store.find_one("ds", {"a": 4})[ROW_ID] == 4

    def test_block_field_update_and_set_field(self, store):
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
        store.insert_columns("ds", {"a": ["1", "2"]})
        store.update_one("ds", {ROW_ID: 1}, {"a": "9", "new": "n"})
        assert store.find_one("ds", {ROW_ID: 1}) == {
            "a": "9",
            "new": "n",
            ROW_ID: 1,
        }
        # Row 2 never got the field: Mongo missing-field semantics — the
        # synthesized document omits it entirely ($exists False).
        assert "new" not in store.find_one("ds", {ROW_ID: 2})
        assert store.find_one("ds", {"new": {"$exists": False}, ROW_ID: 2}) is not None
        store.set_field_values("ds", "a", {1: 10, 2: 20})
        assert store.read_columns("ds", ["a"]) == {"a": [10, 20]}
        # metadata (overlay) survives untouched
        assert store.metadata("ds")["finished"] is False

    def test_generic_query_update_hits_block_row(self, store):
        store.insert_columns("ds", {"a": ["x", "y", "y"]})
        store.update_one("ds", {"a": "y"}, {"a": "z"})  # first match only
        assert store.read_columns("ds", ["a"]) == {"a": ["x", "z", "y"]}

    def test_read_columns_mixed_overlay_fallback(self, store):
        store.insert_columns("ds", {"a": [1, 2]})
        store.insert_one("ds", {ROW_ID: 10, "a": 5})  # stray overlay row
        assert store.read_columns("ds", ["a"]) == {"a": [1, 2, 5]}

    def test_wal_replays_columnar_block(self, tmp_path):
        data_dir = str(tmp_path / "wal")
        first = InMemoryStore(data_dir=data_dir)
        first.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
        first.insert_columns("ds", {"a": ["1", "2"]})
        second = InMemoryStore(data_dir=data_dir)
        assert list(second.find("ds", {ROW_ID: {"$gt": 0}})) == [
            {"a": "1", ROW_ID: 1},
            {"a": "2", ROW_ID: 2},
        ]
        # and through compaction
        second.compact()
        third = InMemoryStore(data_dir=data_dir)
        assert third.read_columns("ds", ["a"]) == {"a": ["1", "2"]}
        assert third.metadata("ds")["finished"] is True

    def test_aggregate_group_fast_path(self, store):
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
        store.insert_columns("ds", {"s": ["a", "b", "a", None]})
        result = store.aggregate("ds", [{"$group": {"_id": "$s", "count": {"$sum": 1}}}])
        assert {r["_id"]: r["count"] for r in result} == {"a": 2, "b": 1, None: 1}

    def test_pagination_on_block(self, store):
        store.insert_columns("ds", {"a": list(range(100))})
        docs = list(store.find("ds", skip=95, limit=10))
        assert [d[ROW_ID] for d in docs] == [96, 97, 98, 99, 100]

    def test_padded_fields_never_leak_missing_sentinel(self, store):
        # Adding a field to one block row pads the others; the pads must
        # read as None via every columnar exit, never as the sentinel.
        store.insert_columns("ds", {"a": [1, 2, 3]})
        store.update_one("ds", {ROW_ID: 2}, {"new": "n"})
        cols = store.read_columns("ds", ["new"])
        assert cols == {"new": [None, "n", None]}
        assert all(v is None or isinstance(v, str) for v in cols["new"])
        result = store.aggregate(
            "ds", [{"$group": {"_id": "$new", "count": {"$sum": 1}}}]
        )
        assert {r["_id"]: r["count"] for r in result} == {None: 2, "n": 1}
        # and the whole payload is JSON-serializable (the wire contract)
        json.dumps(cols), json.dumps(result)

    def test_compact_serializes_pads_and_survives(self, tmp_path):
        data_dir = str(tmp_path / "wal")
        store = InMemoryStore(data_dir=data_dir)
        store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
        store.insert_columns("ds", {"a": [1, 2, 3]})
        store.update_one("ds", {ROW_ID: 2}, {"new": "n"})
        store.compact()  # must not TypeError on the _Missing pads
        # writes still work after compaction (WAL handle reopened)
        store.insert_one("ds", {"a": 4})
        replayed = InMemoryStore(data_dir=data_dir)
        # pads survive the snapshot as true missing fields, not nulls
        assert "new" not in replayed.find_one("ds", {ROW_ID: 1})
        assert replayed.find_one("ds", {ROW_ID: 2})["new"] == "n"
        assert (
            replayed.find_one("ds", {ROW_ID: 3, "new": {"$exists": False}})
            is not None
        )
        assert replayed.find_one("ds", {"a": 4}) is not None
        assert replayed.metadata("ds")["finished"] is True


def test_set_column_block_replace_and_wal(tmp_path):
    data_dir = str(tmp_path / "wal")
    store = InMemoryStore(data_dir=data_dir)
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": True})
    store.insert_columns("ds", {"a": ["1", "2", "3"]})
    store.set_column("ds", "a", [1, 2, 3])
    store.set_column("ds", "b", ["x", "y", "z"])  # brand-new field
    assert store.read_columns("ds", ["a", "b"]) == {
        "a": [1, 2, 3],
        "b": ["x", "y", "z"],
    }
    replayed = InMemoryStore(data_dir=data_dir)
    assert replayed.read_columns("ds", ["a", "b"]) == {
        "a": [1, 2, 3],
        "b": ["x", "y", "z"],
    }


def test_set_column_partial_range(store):
    store.insert_columns("ds", {"a": [0, 0, 0, 0]})
    store.set_column("ds", "a", [7, 8], start_id=2)
    assert store.read_columns("ds", ["a"]) == {"a": [0, 7, 8, 0]}


def test_insert_columns_rejects_id_column(store):
    with pytest.raises(ValueError):
        store.insert_columns("ds", {"_id": [5, 6], "a": [1, 2]})


def test_update_one_operator_query_on_id(store):
    store.insert_columns("ds", {"a": ["x", "y", "z"]})
    store.update_one("ds", {ROW_ID: {"$gt": 2}}, {"a": "Z"})
    assert store.read_columns("ds", ["a"]) == {"a": ["x", "y", "Z"]}


def test_aggregate_group_by_id_fast_path(store):
    store.insert_columns("ds", {"a": ["x", "y"]})
    result = store.aggregate("ds", [{"$group": {"_id": "$_id", "count": {"$sum": 1}}}])
    assert sorted((r["_id"], r["count"]) for r in result) == [(1, 1), (2, 1)]


def test_ingest_csv_with_id_header_column(store, tmp_path):
    from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata

    path = tmp_path / "withid.csv"
    path.write_text("_id,name\n99,alice\n98,bob\n")
    write_ingest_metadata(store, "w", str(path))
    ingest_csv(store, "w", str(path))
    rows = list(store.find("w", {ROW_ID: {"$gt": 0}}))
    # CSV _id column discarded; row ids are always 1..N (reference parity)
    assert [r[ROW_ID] for r in rows] == [1, 2]
    assert [r["name"] for r in rows] == ["alice", "bob"]


def test_compact_does_not_stall_or_lose_concurrent_writes(tmp_path):
    """Compaction serializes the snapshot OUTSIDE the store lock; writes
    landing mid-compaction are captured and survive a WAL replay —
    and readers/writers never wait for the full serialization."""
    import threading

    data_dir = str(tmp_path / "wal")
    store = InMemoryStore(data_dir=data_dir)
    store.insert_one("ds", {ROW_ID: 0, "finished": True})
    store.insert_columns("ds", {"x": list(range(50_000))})

    stop = threading.Event()
    written = []

    def writer():
        i = 0
        while not stop.is_set():
            store.insert_one("side", {ROW_ID: i, "v": i})
            written.append(i)
            i += 1

    thread = threading.Thread(target=writer)
    thread.start()
    try:
        for _ in range(5):
            store.compact()
    finally:
        stop.set()
        thread.join()

    # one more write after the last compaction, then replay everything
    store.insert_one("side", {ROW_ID: len(written), "v": -1})
    reopened = InMemoryStore(data_dir=data_dir)
    assert reopened.count("ds") == 50_001
    assert reopened.count("side") == len(written) + 1
    assert reopened.read_columns("ds", ["x"])["x"][:3] == [0, 1, 2]


def test_compact_drop_mid_serialization_respected(tmp_path):
    """A collection dropped WHILE the snapshot is being serialized
    (compaction phase B, outside the lock) must stay dropped after
    replay: the snapshot still contains the collection's records, and
    correctness depends on the side-captured drop replaying after
    them."""
    data_dir = str(tmp_path / "wal2")
    store = InMemoryStore(data_dir=data_dir)
    store.insert_columns("keep", {"x": [1, 2, 3]})
    store.insert_columns("gone", {"y": [4, 5]})

    original = store._snapshot_records_of

    def dropping_mid_stream(collections):
        for i, record in enumerate(original(collections)):
            if i == 0:
                store.drop("gone")  # lands during phase B, via side capture
            yield record

    store._snapshot_records_of = dropping_mid_stream
    try:
        assert store.compact() is True
    finally:
        store._snapshot_records_of = original
    reopened = InMemoryStore(data_dir=data_dir)
    assert "keep" in reopened.list_collections()
    assert "gone" not in reopened.list_collections()


def test_compact_abandoned_when_resync_supersedes(tmp_path):
    """A replication resync landing mid-compaction must WIN: the
    in-flight compaction abandons (returns False) instead of publishing
    its stale snapshot over the resynced log."""
    import json as _json

    data_dir = str(tmp_path / "wal3")
    store = InMemoryStore(data_dir=data_dir, replicate=True)
    store.insert_columns("old", {"x": [1, 2]})

    new_lines = [
        _json.dumps({"op": "epoch", "e": 7}),
        _json.dumps({"op": "create", "c": "fresh"}),
        _json.dumps({"op": "insert", "c": "fresh", "d": {ROW_ID: 1, "v": 9}}),
    ]
    original = store._snapshot_records_of

    def resync_mid_stream(collections):
        for i, record in enumerate(original(collections)):
            if i == 0:
                store.resync_apply(new_lines)  # primary resync wins
            yield record

    store._snapshot_records_of = resync_mid_stream
    try:
        assert store.compact() is False
    finally:
        store._snapshot_records_of = original
    # durable log and memory both reflect the resync, not the snapshot
    assert "fresh" in store.list_collections()
    assert "old" not in store.list_collections()
    reopened = InMemoryStore(data_dir=data_dir)
    assert "fresh" in reopened.list_collections()
    assert "old" not in reopened.list_collections()


class TestSpillPolicy:
    """LO_SPILL_BYTES: past the RAM budget the store moves the largest
    column payloads to disk-backed mappings and keeps appending to the
    files — stored bytes >> RAM (the reference's Mongo-owns-disk
    property, docker-compose.yml:335-340)."""

    def _store_with_budget(self, monkeypatch, tmp_path, budget: str):
        monkeypatch.setenv("LO_SPILL_BYTES", budget)
        monkeypatch.setenv("LO_SPILL_DIR", str(tmp_path / "spill"))
        from learningorchestra_tpu.core.store import (
            _SPILL_MIN_COLUMN_BYTES,
            InMemoryStore,
        )

        return InMemoryStore(), _SPILL_MIN_COLUMN_BYTES

    def test_columns_spill_past_budget_and_stay_readable(
        self, monkeypatch, tmp_path
    ):
        import numpy as np

        store, min_bytes = self._store_with_budget(
            monkeypatch, tmp_path, str(32 * 1024 * 1024)
        )
        rows = (min_bytes // 8) + 1024  # one column just past spill size
        store.create_collection("big")
        values = np.arange(rows, dtype=np.float64)
        # six such columns: ~3x the 32MB budget
        store.insert_columns(
            "big", {f"c{i}": values + i for i in range(6)}
        )
        spilled = [
            field
            for field, column in store._collections["big"]
            .block_columns.items()
            if column.is_spilled()
        ]
        assert spilled, "no column spilled past the budget"
        back = store.read_column_arrays("big", ["c0", "c5"])
        assert back["c0"].tolist()[:3] == [0.0, 1.0, 2.0]
        assert back["c5"].tolist()[rows - 1] == float(rows - 1 + 5)
        # appends to a spilled column keep working (streamed to file)
        store.insert_columns(
            "big",
            {f"c{i}": np.array([-1.0]) for i in range(6)},
            start_id=rows + 1,
        )
        assert store.count("big") == rows + 1
        tail = store.read_column_arrays("big", ["c0"])["c0"]
        assert tail.tolist()[-1] == -1.0

    def test_drop_reclaims_spill_files(self, monkeypatch, tmp_path):
        import os

        import numpy as np

        store, min_bytes = self._store_with_budget(
            monkeypatch, tmp_path, "1"
        )
        rows = (min_bytes // 8) + 8
        store.create_collection("gone")
        store.insert_columns(
            "gone", {"x": np.arange(rows, dtype=np.float64)}
        )
        spill_root = str(tmp_path / "spill")
        assert os.path.isdir(spill_root) and os.listdir(spill_root)
        store.drop("gone")
        assert not any(
            files for _, _, files in os.walk(spill_root)
        ), "spill files not reclaimed on drop"

    def test_budget_zero_disables_spill(self, monkeypatch, tmp_path):
        import numpy as np

        store, min_bytes = self._store_with_budget(monkeypatch, tmp_path, "0")
        rows = (min_bytes // 8) + 8
        store.create_collection("ram")
        store.insert_columns("ram", {"x": np.arange(rows, dtype=np.float64)})
        assert not store._collections["ram"].block_columns["x"].is_spilled()

    def _spill_one(self, store, min_bytes, name="big", start_id=None):
        import numpy as np

        store.insert_columns(
            name,
            {"x": np.arange((min_bytes // 8) + 8, dtype=np.float64)},
            start_id=start_id,
        )

    def _spill_files(self, root) -> list:
        import os

        return [
            os.path.join(folder, f)
            for folder, _, files in os.walk(root)
            for f in files
        ]

    def test_resync_reclaims_spill_across_two_cycles(
        self, monkeypatch, tmp_path
    ):
        """Every demotion/fence resync on a spilled follower must
        reclaim the previous generation's spill files AND mappings —
        repeated failovers under an explicit LO_SPILL_DIR must not grow
        disk without bound (ADVICE r5)."""
        import json as _json
        import os

        monkeypatch.setenv("LO_SPILL_BYTES", "1")
        monkeypatch.setenv("LO_SPILL_DIR", str(tmp_path / "spill"))
        from learningorchestra_tpu.core.store import (
            _SPILL_MIN_COLUMN_BYTES,
            InMemoryStore,
        )

        store = InMemoryStore(replicate=True)
        spill_root = str(tmp_path / "spill")
        resync_lines = [_json.dumps({"op": "create", "c": "fresh"})]
        for cycle in range(2):
            self._spill_one(store, _SPILL_MIN_COLUMN_BYTES)
            assert self._spill_files(spill_root), "setup: nothing spilled"
            assert store._spill_folders
            store.resync_apply(resync_lines)
            assert not self._spill_files(spill_root), (
                f"resync cycle {cycle} stranded spill files"
            )
            assert not store._spill_folders, (
                f"resync cycle {cycle} stranded folder mappings"
            )
            store.drop("fresh")  # reset for the next cycle
        assert os.path.isdir(spill_root)  # the root itself is kept

    def test_replicated_drop_reclaims_spill_files(
        self, monkeypatch, tmp_path
    ):
        """A drop arriving over REPLICATION (apply_replicated →
        _apply_record) must reclaim spill files exactly like a direct
        drop() — a follower used to strand the folder and mis-route a
        recreated same-name collection into the stale files."""
        import json as _json

        monkeypatch.setenv("LO_SPILL_BYTES", "1")
        monkeypatch.setenv("LO_SPILL_DIR", str(tmp_path / "spill"))
        from learningorchestra_tpu.core.store import (
            _SPILL_MIN_COLUMN_BYTES,
            InMemoryStore,
        )

        follower = InMemoryStore(replicate=True)
        self._spill_one(follower, _SPILL_MIN_COLUMN_BYTES)
        spill_root = str(tmp_path / "spill")
        assert self._spill_files(spill_root)
        follower.apply_replicated([_json.dumps({"op": "drop", "c": "big"})])
        assert not self._spill_files(spill_root), (
            "replicated drop stranded spill files"
        )
        assert "big" not in follower._spill_folders
