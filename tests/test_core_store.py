"""Core store: Mongo-contract semantics, WAL durability, aggregation."""

import threading

import pytest

from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
    matches,
    parse_query,
)


def test_insert_find_ordering(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False})
    store.insert_many("ds", [{ROW_ID: i, "x": i * 10} for i in range(1, 6)])
    docs = list(store.find("ds"))
    assert [d[ROW_ID] for d in docs] == [0, 1, 2, 3, 4, 5]


def test_skip_limit_pagination(store):
    store.insert_many("ds", [{ROW_ID: i, "x": i} for i in range(10)])
    docs = list(store.find("ds", skip=3, limit=4))
    assert [d[ROW_ID] for d in docs] == [3, 4, 5, 6]


def test_query_subset_match(store):
    store.insert_many(
        "ds",
        [
            {ROW_ID: 1, "a": "x", "b": 1},
            {ROW_ID: 2, "a": "y", "b": 1},
            {ROW_ID: 3, "a": "x", "b": 2},
        ],
    )
    assert [d[ROW_ID] for d in store.find("ds", {"a": "x"})] == [1, 3]
    assert store.find_one("ds", {"a": "y"})[ROW_ID] == 2
    assert store.find_one("ds", {"a": "zzz"}) is None


def test_update_one_sets_fields(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
    store.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True, "fields": ["a"]})
    meta = store.metadata("ds")
    assert meta["finished"] is True and meta["fields"] == ["a"]
    assert store.is_finished("ds")


def test_duplicate_id_rejected(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_one("ds", {ROW_ID: 1})


def test_drop_and_list(store):
    store.insert_one("a", {ROW_ID: 1})
    store.insert_one("b", {ROW_ID: 1})
    assert sorted(store.list_collections()) == ["a", "b"]
    store.drop("a")
    assert store.list_collections() == ["b"]


def test_aggregate_group_count(store):
    # The histogram service's $group pushdown (reference: histogram.py:63-69).
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds"})
    store.insert_many(
        "ds", [{ROW_ID: i, "sex": "m" if i % 3 else "f"} for i in range(1, 10)]
    )
    result = store.aggregate(
        "ds", [{"$group": {"_id": "$sex", "count": {"$sum": 1}}}]
    )
    counts = {row["_id"]: row["count"] for row in result}
    assert counts == {"m": 6, "f": 3}


def test_read_columns_excludes_metadata(store):
    store.insert_one("ds", {ROW_ID: METADATA_ID, "filename": "ds", "fields": ["x"]})
    store.insert_many("ds", [{ROW_ID: i, "x": i, "y": str(i)} for i in range(1, 4)])
    cols = store.read_columns("ds")
    assert cols["x"] == [1, 2, 3]
    assert cols["y"] == ["1", "2", "3"]


def test_wal_replay_roundtrip(tmp_path):
    data_dir = str(tmp_path / "wal")
    first = InMemoryStore(data_dir=data_dir)
    first.insert_one("ds", {ROW_ID: 0, "finished": False})
    first.insert_many("ds", [{ROW_ID: 1, "x": 1}, {ROW_ID: 2, "x": 2}])
    first.update_one("ds", {ROW_ID: 0}, {"finished": True})
    first.insert_one("gone", {ROW_ID: 1})
    first.drop("gone")

    reopened = InMemoryStore(data_dir=data_dir)
    assert reopened.list_collections() == ["ds"]
    assert reopened.metadata("ds")["finished"] is True
    assert reopened.count("ds") == 3

    reopened.compact()
    compacted = InMemoryStore(data_dir=data_dir)
    assert compacted.count("ds") == 3


def test_concurrent_inserts_thread_safe(store):
    def writer(start):
        store.insert_many("ds", [{ROW_ID: start + i} for i in range(100)])

    threads = [threading.Thread(target=writer, args=(i * 100,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert store.count("ds") == 800


def test_parse_query_accepts_json_and_python_repr():
    assert parse_query("{}") == {}
    assert parse_query('{"a": 1}') == {"a": 1}
    assert parse_query("{'a': 1}") == {"a": 1}  # reference client's str(dict)
    assert parse_query(None) == {}


def test_matches_subset():
    assert matches({"a": 1, "b": 2}, {"a": 1})
    assert not matches({"a": 1}, {"a": 2})
    assert not matches({"a": 1}, {"missing": 1})


def test_insert_many_atomic_on_duplicate(store):
    store.insert_one("ds", {ROW_ID: 1})
    with pytest.raises(KeyError):
        store.insert_many("ds", [{ROW_ID: 5}, {ROW_ID: 1}])
    # nothing from the failed batch was applied
    assert [d[ROW_ID] for d in store.find("ds")] == [1]


def test_job_manager_rejects_active_duplicate_name():
    import time as _time

    from learningorchestra_tpu.core.jobs import JobManager

    jm = JobManager()
    jm.submit("j", _time.sleep, 0.3)
    with pytest.raises(ValueError):
        jm.submit("j", _time.sleep, 0.01)
    jm.wait("j", timeout=5)
    jm.submit("j", _time.sleep, 0.01)  # allowed after completion
    assert jm.wait("j", timeout=5).state == "finished"


def test_matches_query_operators():
    doc = {"a": 5, "s": "x"}
    assert matches(doc, {"a": {"$gt": 4}})
    assert not matches(doc, {"a": {"$gt": 5}})
    assert matches(doc, {"a": {"$gte": 5, "$lte": 5}})
    assert matches(doc, {"a": {"$lt": 6}})
    assert not matches(doc, {"a": {"$lt": 5}})
    assert matches(doc, {"a": {"$ne": 4}})
    assert not matches(doc, {"a": {"$ne": 5}})
    assert matches(doc, {"a": {"$eq": 5}})
    assert matches(doc, {"s": {"$in": ["x", "y"]}})
    assert not matches(doc, {"s": {"$nin": ["x", "y"]}})
    assert matches(doc, {"missing": {"$exists": False}})
    assert matches(doc, {"a": {"$exists": True}})
    assert not matches(doc, {"a": {"$exists": False}})
    # operator on a missing key never matches
    assert not matches(doc, {"missing": {"$gt": 0}})
    # incomparable types (None vs number) are a non-match, not an error
    assert not matches({"a": None}, {"a": {"$gt": 0}})
    # a non-operator dict value still means plain equality
    assert matches({"a": {"x": 1}}, {"a": {"x": 1}})


def test_find_with_operator_query(store):
    store.insert_many("ds", [{ROW_ID: i, "x": i} for i in range(1, 8)])
    assert [d[ROW_ID] for d in store.find("ds", {"x": {"$gte": 3, "$lt": 6}})] == [
        3,
        4,
        5,
    ]
    assert [d[ROW_ID] for d in store.find("ds", {"x": {"$in": [2, 7]}})] == [2, 7]


def test_create_collection_atomic_claim(store):
    assert store.create_collection("ds") is True
    assert store.create_collection("ds") is False
    assert "ds" in store.list_collections()
    # claimed collection accepts documents as usual
    store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
    assert store.metadata("ds")["finished"] is False


def test_create_collection_concurrent_single_winner(store):
    wins = []
    barrier = threading.Barrier(8)

    def claim():
        barrier.wait()
        if store.create_collection("target"):
            wins.append(1)

    threads = [threading.Thread(target=claim) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1


def test_wal_replays_created_empty_collection(tmp_path):
    data_dir = str(tmp_path / "wal")
    first = InMemoryStore(data_dir=data_dir)
    first.create_collection("claimed")
    first.insert_one("full", {ROW_ID: 1})
    first.compact()
    second = InMemoryStore(data_dir=data_dir)
    assert sorted(second.list_collections()) == ["claimed", "full"]


def test_matches_mongo_missing_field_and_logicals():
    from learningorchestra_tpu.core.store import UnsupportedQueryError

    # $ne / $nin match documents lacking the field (Mongo semantics)
    assert matches({"a": 1}, {"b": {"$ne": 5}})
    assert matches({"a": 1}, {"b": {"$nin": [5]}})
    assert not matches({"b": 5}, {"b": {"$ne": 5}})
    # $regex
    assert matches({"s": "hello"}, {"s": {"$regex": "ell"}})
    assert not matches({"s": "hello"}, {"s": {"$regex": "^x"}})
    assert not matches({"s": 5}, {"s": {"$regex": "5"}})
    # $not
    assert matches({"a": 1}, {"a": {"$not": {"$gt": 5}}})
    assert not matches({"a": 9}, {"a": {"$not": {"$gt": 5}}})
    # top-level logicals
    assert matches({"a": 1}, {"$or": [{"a": 1}, {"a": 2}]})
    assert not matches({"a": 3}, {"$or": [{"a": 1}, {"a": 2}]})
    assert matches({"a": 1, "b": 2}, {"$and": [{"a": 1}, {"b": 2}]})
    assert matches({"a": 3}, {"$nor": [{"a": 1}, {"a": 2}]})
    # unknown operators raise (REST maps to 400) instead of silent no-match
    with pytest.raises(UnsupportedQueryError):
        matches({"a": 1}, {"a": {"$mod": [2, 0]}})
    with pytest.raises(UnsupportedQueryError):
        matches({"a": 1}, {"$where": "1"})


def test_ingest_claim_shares_create_collection_gate(store, titanic_csv):
    from learningorchestra_tpu.core.ingest import write_ingest_metadata

    assert store.create_collection("claimed")
    with pytest.raises(KeyError):
        write_ingest_metadata(store, "claimed", titanic_csv)
