"""The crash-resume chaos drill (docs/robustness.md): kill -9 a runner
mid-5-classifier build, restart it on the same WAL, and prove the build
reaches FINISHED with metrics equal to an uninterrupted run — the
journal re-enqueued the orphaned job, the fits resumed from their
progress artifacts (segments skipped, not re-run), and no acknowledged
ingest row was lost.

Slow by design (two full runner boots + six classifier fits); the fast
unit halves of every claim here live in tests/test_resume.py.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)

CLASSIFIERS = ["lr", "dt", "rf", "gb", "nb"]

PREPROCESSOR = (
    "from pyspark.ml.feature import VectorAssembler\n"
    "assembler = VectorAssembler(inputCols=['f1', 'f2'],"
    " outputCol='features')\n"
    "features_training = assembler.transform(training_df)\n"
    "features_testing = assembler.transform(testing_df)\n"
    "features_evaluation = features_training\n"
)


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as error:
        return error.code, error.read()


def _get_json(url, timeout=30):
    status, raw = _get(url, timeout)
    return status, json.loads(raw)


def _request(url, body, method="POST", timeout=60):
    request = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    with urllib.request.urlopen(request, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


class _Runner:
    """One services.runner subprocess on ephemeral ports."""

    def __init__(self, data_dir, models_dir, env_extra=None):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
        env["PYTHONUNBUFFERED"] = "1"
        env["LO_EPHEMERAL"] = "1"
        env["LO_DATA_DIR"] = str(data_dir)
        env["LO_MODELS_DIR"] = str(models_dir)
        # one classifier at a time: the kill reliably lands while later
        # members are still queued, maximizing the resumed run's work
        env["LO_BUILD_WORKERS"] = "1"
        env.update(env_extra or {})
        self.process = subprocess.Popen(
            [sys.executable, "-m", "learningorchestra_tpu.services.runner"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            cwd=_REPO_ROOT,
        )
        self.ports: dict[str, int] = {}
        self.boot_lines: list[str] = []

    def wait_serving(self, timeout_s=300) -> None:
        deadline = time.time() + timeout_s
        service_re = re.compile(r"service (\w+) on [\d.]+:(\d+)")
        while time.time() < deadline:
            line = self.process.stdout.readline()
            if not line:
                raise AssertionError(
                    "runner died during bring-up:\n"
                    + "".join(self.boot_lines)
                )
            self.boot_lines.append(line)
            match = service_re.search(line)
            if match:
                self.ports[match.group(1)] = int(match.group(2))
            if "serving all services" in line:
                return
        raise AssertionError(
            "runner never served:\n" + "".join(self.boot_lines)
        )

    def url(self, service: str, path: str) -> str:
        return f"http://127.0.0.1:{self.ports[service]}{path}"

    def kill9(self) -> int:
        os.kill(self.process.pid, signal.SIGKILL)
        return self.process.wait(timeout=30)

    def terminate(self) -> None:
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.process.kill()


def _ingest(runner, name, csv_path, deadline_s=120) -> None:
    status, _ = _request(
        runner.url("database_api", "/files"),
        {"filename": name, "url": str(csv_path)},
    )
    assert status == 201
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        status, body = _get_json(
            runner.url(
                "database_api", f"/files/{name}?skip=0&limit=1&query={{}}"
            )
        )
        if status == 200 and body["result"][0].get("finished"):
            break
        time.sleep(0.2)
    else:
        raise AssertionError(f"ingest of {name} never finished")
    status, _ = _request(
        runner.url("data_type_handler", f"/fieldtypes/{name}"),
        {"f1": "number", "f2": "number", "label": "number"},
        method="PATCH",
    )
    assert status == 200


def _build(runner, name, classifiers, asynchronous=False, timeout=600):
    body = {
        "training_filename": name,
        "test_filename": name,
        "preprocessor_code": PREPROCESSOR,
        "classificators_list": list(classifiers),
    }
    if asynchronous:
        body["async"] = True
    return _request(
        runner.url("model_builder", "/models"), body, timeout=timeout
    )


def _prediction_metadata(runner, name, classifier):
    status, body = _get_json(
        runner.url(
            "database_api",
            f"/files/{name}_prediction_{classifier}"
            "?skip=0&limit=1&query={}",
        )
    )
    if status != 200 or not body.get("result"):
        return None
    metadata = body["result"][0]
    return metadata if "accuracy" in metadata else None


def _journal_has_segment_event(runner) -> bool:
    skip = 0
    while True:
        status, body = _get_json(
            runner.url(
                "database_api",
                f"/files/__lo_jobs__?skip={skip}&limit=20&query={{}}",
            )
        )
        if status != 200:
            return False
        page = body.get("result") or []
        if not page:
            return False
        if any(
            doc.get("event") == "progress" and doc.get("kind") == "segment"
            for doc in page
        ):
            return True
        skip += len(page)


def _metric_value(metrics_text: str, name: str) -> float:
    total = 0.0
    seen = False
    for line in metrics_text.splitlines():
        if line.startswith("#"):
            continue
        match = re.match(rf"^{re.escape(name)}(?:\{{.*\}})?\s+([\d.eE+-]+)$", line)
        if match:
            total += float(match.group(1))
            seen = True
    assert seen, f"{name} missing from /metrics"
    return total


@pytest.mark.slow
@pytest.mark.integration
def test_kill9_mid_build_resumes_to_identical_metrics(tmp_path):
    data_dir = tmp_path / "lo_data"
    models_dir = tmp_path / "models"
    csv_path = tmp_path / "drill.csv"
    with open(csv_path, "w") as f:
        # features stay non-negative: NaiveBayes (the 5th classifier)
        # enforces the MLlib non-negativity contract
        f.write("f1,f2,label\n")
        for i in range(120):
            lab = i % 2
            f.write(
                f"{lab * 2 + (i % 7) * 0.1:.3f},"
                f"{(1 - lab) * 2 + (i % 5) * 0.1:.3f},{lab}\n"
            )

    # Phase delays stretch every per-classifier phase boundary so the
    # SIGKILL below reliably lands mid-build (never between builds),
    # without changing any computed number.
    first = _Runner(
        data_dir,
        models_dir,
        env_extra={"LO_FAULT_BUILDER_PHASE": "delay:0.5@100"},
    )
    second = None
    try:
        first.wait_serving()
        _ingest(first, "drill", csv_path)
        status, body = _build(
            first, "drill", CLASSIFIERS, asynchronous=True, timeout=30
        )
        assert status == 201
        job_name = body["job"]
        assert job_name == "build:drill:" + "+".join(CLASSIFIERS)

        # the moment a fit-segment progress event is durably journaled,
        # the build is provably mid-flight — pull the plug
        deadline = time.time() + 300
        while time.time() < deadline:
            if _journal_has_segment_event(first):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("no segment progress event ever journaled")
        returncode = first.kill9()
        assert returncode == -signal.SIGKILL

        # same WAL, same models volume, no faults: recovery must
        # re-enqueue the orphaned build and finish it
        second = _Runner(data_dir, models_dir)
        second.wait_serving()
        assert any(
            "job recovery: 1 re-enqueued" in line
            for line in second.boot_lines
        ), "".join(second.boot_lines)

        resumed: dict[str, dict] = {}
        deadline = time.time() + 600
        while time.time() < deadline and len(resumed) < len(CLASSIFIERS):
            for name in CLASSIFIERS:
                if name not in resumed:
                    metadata = _prediction_metadata(second, "drill", name)
                    if metadata is not None:
                        resumed[name] = metadata
            time.sleep(0.5)
        assert sorted(resumed) == sorted(CLASSIFIERS), (
            f"resumed build incomplete: {sorted(resumed)}"
        )

        # the resumed job itself reached FINISHED (not a fresh rebuild
        # under another name): its record is queryable on the new runner
        status, body = _get_json(
            second.url("model_builder", f"/jobs/{job_name}")
        )
        assert status == 200
        assert body["result"]["state"] == "finished"

        # zero acknowledged ingest rows lost across the kill (the file
        # read pages at 20 documents, reference parity — walk them all)
        rows = []
        skip = 0
        while True:
            status, body = _get_json(
                second.url(
                    "database_api",
                    f"/files/drill?skip={skip}&limit=20&query={{}}",
                )
            )
            assert status == 200
            page = body["result"]
            if not page:
                break
            rows.extend(d for d in page if d.get("_id", 0) != 0)
            skip += len(page)
        assert len(rows) == 120

        # resume telemetry: the orphaned job was resumed (not replayed
        # from scratch) and at least one fit segment was restored from
        # a progress artifact instead of re-running
        status, raw = _get(second.url("database_api", "/metrics"))
        assert status == 200
        metrics_text = raw.decode()
        assert _metric_value(metrics_text, "lo_sched_resumed_total") >= 1
        assert (
            _metric_value(metrics_text, "lo_build_segments_skipped_total")
            >= 1
        )

        # the headline: a control build of the same data on the healthy
        # runner produces THE SAME metrics — resume changed wall-clock,
        # never a number
        _ingest(second, "drill_ctl", csv_path)
        status, _ = _build(second, "drill_ctl", CLASSIFIERS, timeout=600)
        assert status == 201
        for name in CLASSIFIERS:
            control = _prediction_metadata(second, "drill_ctl", name)
            assert control is not None, f"control build missing {name}"
            assert resumed[name]["accuracy"] == control["accuracy"], name
            assert resumed[name].get("F1") == control.get("F1"), name
    finally:
        first.terminate()
        if second is not None:
            second.terminate()
