"""ColumnTable: typing, encoding, matrices, store round-trips."""

import numpy as np

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.store import ROW_ID
from learningorchestra_tpu.core.table import ColumnTable, write_table


def test_column_typing_and_nan():
    table = ColumnTable.from_lists(
        {"num": [1, 2.5, None], "txt": ["a", None, "b"], "mixed": [1, "x", 2]}
    )
    assert table.dtype_of("num") == "number"
    assert table.dtype_of("txt") == "string"
    assert table.dtype_of("mixed") == "string"
    assert np.isnan(table.columns["num"][2])
    assert table.number_fields() == ["num"]
    assert sorted(table.string_fields()) == ["mixed", "txt"]


def test_dropna_both_kinds():
    table = ColumnTable.from_lists({"num": [1, None, 3], "txt": ["a", "b", None]})
    clean = table.dropna()
    assert clean.num_rows == 1
    assert clean.columns["num"][0] == 1 and clean.columns["txt"][0] == "a"


def test_encoded_matches_label_encoder_order():
    # Codes in sorted order — the sklearn LabelEncoder convention the
    # reference relies on (reference: pca.py:79-85).
    table = ColumnTable.from_lists({"s": ["b", "a", "c", "a"]})
    encoded, vocab = table.encoded()
    assert vocab["s"] == ["a", "b", "c"]
    np.testing.assert_array_equal(encoded.columns["s"], [1.0, 0.0, 2.0, 0.0])


def test_matrix_shape_and_order():
    table = ColumnTable.from_lists({"a": [1, 2], "b": [3, 4]})
    mat = table.matrix(["b", "a"])
    np.testing.assert_array_equal(mat, [[3, 1], [4, 2]])


def test_store_roundtrip(store):
    table = ColumnTable.from_lists({"x": [1.0, 2.0], "s": ["u", "v"]})
    write_table(store, "out", table, {"filename": "out", "finished": True})
    assert store.metadata("out")["filename"] == "out"
    back = ColumnTable.from_store(store, "out")
    np.testing.assert_array_equal(back.columns["x"], [1.0, 2.0])
    assert list(back.columns["s"]) == ["u", "v"]


def test_ingest_csv_contract(store, titanic_csv):
    write_ingest_metadata(store, "titanic", titanic_csv)
    meta = store.metadata("titanic")
    assert meta["finished"] is False and meta["fields"] == "processing"

    n = ingest_csv(store, "titanic", titanic_csv)
    assert n == 8
    meta = store.metadata("titanic")
    assert meta["finished"] is True
    assert meta["fields"][:3] == ["PassengerId", "Survived", "Pclass"]
    rows = list(store.find("titanic", skip=1, limit=2))
    assert rows[0][ROW_ID] == 1
    # values stored as raw strings; missing cell preserved as empty string
    assert rows[0]["Age"] == "22"
    row6 = store.find_one("titanic", {ROW_ID: 6})
    assert row6["Age"] == ""
    # quoted comma survives
    assert rows[0]["Name"] == "Braund, Mr. Owen"


def test_ingest_rejects_html(store, tmp_path):
    import pytest

    from learningorchestra_tpu.core.ingest import IngestError, validate_csv_url

    bad = tmp_path / "page.html"
    bad.write_text("<html><body>hi</body></html>")
    with pytest.raises(IngestError):
        validate_csv_url(str(bad))


def test_ingest_preserves_embedded_newlines(store, tmp_path):
    path = tmp_path / "multiline.csv"
    path.write_text('id,note\n1,"line1\nline2"\n2,plain\n')
    from learningorchestra_tpu.core.ingest import ingest_csv

    n = ingest_csv(store, "ml", str(path))
    assert n == 2
    assert store.find_one("ml", {ROW_ID: 1})["note"] == "line1\nline2"
