"""Worker process for the 2-process multi-host test (not a pytest file).

Launched by tests/test_multihost.py: each worker joins a 2-process
jax.distributed runtime (4 virtual CPU devices per process, 8 global),
builds the standard (data, model) mesh over the GLOBAL device list, and
fits the same LR job twice:

- SPMD path: every process passes the full global dataset (the
  single-host call signature, unchanged);
- per-host feeding path: each process loads only its
  ``host_row_range`` slice and the global array is assembled with
  ``shard_rows_local`` — no host materializes all rows.

Results (accuracy, predictions, probabilities) are written to a JSON
file per process; the parent asserts both processes agree with each
other and with a single-process 8-device run of the identical job.
"""

import json
import sys


def main() -> None:
    process_id = int(sys.argv[1])
    num_processes = int(sys.argv[2])
    coordinator = sys.argv[3]
    out_path = sys.argv[4]

    import os

    os.environ["LO_COORDINATOR"] = coordinator
    os.environ["LO_NUM_PROCESSES"] = str(num_processes)
    os.environ["LO_PROCESS_ID"] = str(process_id)

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)

    from learningorchestra_tpu.parallel.multihost import (
        fetch,
        host_row_range,
        initialize_from_env,
        shard_rows_local,
    )

    assert initialize_from_env(), "multi-host runtime did not come up"
    assert jax.process_count() == num_processes

    import numpy as np

    from learningorchestra_tpu.ml.logistic import LogisticRegression
    from learningorchestra_tpu.parallel.mesh import make_mesh
    from learningorchestra_tpu.parallel.sharding import shard_rows

    from multihost_dataset import make_dataset  # noqa: deterministic fixture

    X, y = make_dataset()
    mesh = make_mesh()  # all 8 global devices on the data axis

    model = LogisticRegression(max_iter=25).fit(X, y)
    pred = model.predict(X)
    probs = model.predict_proba(X)
    accuracy = float((pred == y).mean())

    # Per-host feeding: this process loads ONLY its row slice; assert the
    # assembled global array round-trips to the full dataset.
    start, stop = host_row_range(len(X), mesh)
    arr, mask = shard_rows_local(X[start:stop], mesh, len(X), dtype=np.float32)
    global_arr, global_mask = shard_rows(X.astype(np.float32), mesh)
    feeding_ok = bool(
        np.array_equal(fetch(arr), fetch(global_arr))
        and np.array_equal(fetch(mask), fetch(global_mask))
    )

    # ... and fit straight from the per-host-fed shards (device-side
    # standardization; no host ever held the full feature matrix).
    y_arr, _ = shard_rows_local(y[start:stop], mesh, len(y), dtype=np.int32)
    sharded_model = LogisticRegression(max_iter=25).fit_sharded(
        arr, y_arr, mask, num_classes=int(y.max()) + 1
    )
    sharded_pred = sharded_model.predict(X)
    sharded_agreement = float((sharded_pred == pred).mean())

    with open(out_path, "w") as f:
        json.dump(
            {
                "process_id": process_id,
                "global_devices": jax.device_count(),
                "local_devices": jax.local_device_count(),
                "accuracy": accuracy,
                "predictions": pred.tolist(),
                "probs_head": np.asarray(probs)[:8].tolist(),
                "feeding_ok": feeding_ok,
                "sharded_fit_agreement": sharded_agreement,
                "host_rows": [start, stop],
            },
            f,
        )


if __name__ == "__main__":
    main()
