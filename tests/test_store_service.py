"""Store-as-a-service: wire-protocol conformance of RemoteStore against
a live store server, and the full seven-processes-plus-store topology
(the reference's container layout, docker-compose.yml:173-330)."""

import json
import os
import subprocess
import sys
import time

import pytest

from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
    UnsupportedQueryError,
)
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    create_store_app,
)
from learningorchestra_tpu.utils.web import ServerThread

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def remote_store():
    server = ServerThread(create_store_app(InMemoryStore()), "127.0.0.1", 0).start()
    yield RemoteStore(f"http://127.0.0.1:{server.port}")
    server.stop()


class TestRemoteStoreConformance:
    def test_collection_lifecycle(self, remote_store):
        assert remote_store.create_collection("ds") is True
        assert remote_store.create_collection("ds") is False
        assert remote_store.list_collections() == ["ds"]
        remote_store.drop("ds")
        assert remote_store.list_collections() == []

    def test_documents_roundtrip(self, remote_store):
        remote_store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
        remote_store.insert_many("ds", [{ROW_ID: 1, "a": "x"}, {ROW_ID: 2, "a": "y"}])
        assert remote_store.count("ds") == 3
        assert remote_store.find_one("ds", {"a": "y"}) == {ROW_ID: 2, "a": "y"}
        remote_store.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True})
        assert remote_store.is_finished("ds")

    def test_columnar_roundtrip(self, remote_store):
        remote_store.insert_columns("ds", {"a": ["1", "2", "3"], "b": [1.5, None, 3.0]})
        assert remote_store.read_columns("ds", ["a", "b", ROW_ID]) == {
            "a": ["1", "2", "3"],
            "b": [1.5, None, 3.0],
            ROW_ID: [1, 2, 3],
        }
        remote_store.set_column("ds", "a", [1, 2, 3])
        assert remote_store.read_columns("ds", ["a"]) == {"a": [1, 2, 3]}
        remote_store.set_field_values("ds", "b", {2: 9.0})
        assert remote_store.read_columns("ds", ["b"]) == {"b": [1.5, 9.0, 3.0]}

    def test_find_operators_and_pagination(self, remote_store):
        remote_store.insert_columns("ds", {"x": list(range(10))})
        docs = list(remote_store.find("ds", {"x": {"$gte": 5}}, skip=1, limit=2))
        assert [d["x"] for d in docs] == [6, 7]

    def test_read_columns_paged_on_wire(self, remote_store):
        """The read data plane travels in bounded chunks: with a tiny
        wire_rows the same columns come back from multiple small bodies,
        byte-identical to one big read."""
        remote_store.insert_columns(
            "ds", {"a": list(range(25)), "b": [str(i) for i in range(25)]}
        )
        calls = []
        original = remote_store._post

        def counting_post(path, payload):
            if path.endswith("/read_columns"):
                calls.append(payload)
            return original(path, payload)

        remote_store._post = counting_post
        try:
            remote_store.wire_rows = 7
            paged = remote_store.read_columns("ds", ["a", "b"])
            remote_store.wire_rows = 100000
            full = remote_store.read_columns("ds", ["a", "b"])
        finally:
            remote_store._post = original
        assert paged == full
        assert len(calls) >= 4  # 25 rows / 7 per chunk
        assert all(c["limit"] <= 7 for c in calls[:4])

    def test_read_columns_start_limit(self, remote_store):
        remote_store.insert_columns("ds", {"x": list(range(10))})
        assert remote_store.read_columns("ds", ["x"], start=3, limit=4) == {
            "x": [3, 4, 5, 6]
        }

    def test_degenerate_wire_rows_never_spins(self, remote_store):
        remote_store.insert_columns("ds", {"x": [1, 2]})
        remote_store.wire_rows = 0  # e.g. LO_WIRE_ROWS misconfigured
        assert remote_store.read_columns("ds", ["x"])["x"] == []

    def test_aggregate_group(self, remote_store):
        remote_store.insert_columns("ds", {"s": ["a", "b", "a"]})
        result = remote_store.aggregate(
            "ds", [{"$group": {"_id": "$s", "count": {"$sum": 1}}}]
        )
        assert {r["_id"]: r["count"] for r in result} == {"a": 2, "b": 1}

    def test_error_mapping(self, remote_store):
        remote_store.insert_one("ds", {ROW_ID: 1})
        with pytest.raises(KeyError):
            remote_store.insert_one("ds", {ROW_ID: 1})
        with pytest.raises(UnsupportedQueryError):
            list(remote_store.find("ds", {"a": {"$mod": [2, 0]}}))
        with pytest.raises(ValueError):
            remote_store.insert_columns("ds", {"a": [1], "b": [1, 2]})

    def test_services_run_against_remote_store(self, remote_store, titanic_csv):
        """The service layer is store-backend agnostic: the projection
        service works unchanged over the wire protocol."""
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
        from learningorchestra_tpu.services import projection

        write_ingest_metadata(remote_store, "titanic", titanic_csv)
        ingest_csv(remote_store, "titanic", titanic_csv)
        client = projection.create_app(remote_store).test_client()
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "proj", "fields": ["Name", "Age"]},
        )
        assert response.status_code == 201
        assert remote_store.is_finished("proj")
        assert remote_store.read_columns("proj", ["Name"])["Name"][0] == "Braund, Mr. Owen"


def _spawn(env_extra, *argv):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    # services must come up fast and CPU-only in tests
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, *argv],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
    )


def _wait_port_line(process, marker, timeout=120):
    """Read stdout until the bring-up line appears; returns the line."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = process.stdout.readline()
        if not line:
            if process.poll() is not None:
                raise RuntimeError(f"process died (rc={process.returncode})")
            time.sleep(0.05)
            continue
        if marker in line:
            return line.strip()
    raise TimeoutError(f"no {marker!r} line within {timeout}s")


@pytest.mark.integration
def test_multiprocess_stack_titanic(tmp_path, titanic_csv):
    """Every service in its own OS process against one store server —
    the reference's deployment topology, driven by the unchanged client
    (VERDICT round 1, next-round item 3)."""
    import learningorchestra_tpu.client as lo

    processes = []
    try:
        store_proc = _spawn(
            {"LO_STORE_PORT": "0", "LO_DATA_DIR": str(tmp_path / "store")},
            "-m",
            "learningorchestra_tpu.core.store_service",
        )
        processes.append(store_proc)
        line = _wait_port_line(store_proc, "store server on ")
        store_port = int(line.split("store server on ")[1].split()[0].rsplit(":", 1)[1])
        store_url = f"http://127.0.0.1:{store_port}"

        ports = {}
        for name in (
            "database_api",
            "projection",
            "model_builder",
            "data_type_handler",
            "histogram",
            "tsne",
            "pca",
        ):
            proc = _spawn(
                {
                    "LO_SERVICE": name,
                    "LO_PORT": "0",
                    "LO_STORE_URL": store_url,
                    "LO_IMAGES_DIR": str(tmp_path / "images"),
                },
                "-m",
                "learningorchestra_tpu.services.runner",
            )
            processes.append(proc)
            line = _wait_port_line(proc, f"service {name} on ")
            ports[name] = int(line.rsplit(":", 1)[1])

        saved = {}
        port_attrs = {
            "database_api": (lo.DatabaseApi, "DATABASE_API_PORT"),
            "projection": (lo.Projection, "PROJECTION_PORT"),
            "model_builder": (lo.Model, "MODEL_BUILDER_PORT"),
            "data_type_handler": (lo.DataTypeHandler, "DATA_TYPE_HANDLER_PORT"),
            "histogram": (lo.Histogram, "HISTOGRAM_PORT"),
            "tsne": (lo.Tsne, "TSNE_PORT"),
            "pca": (lo.Pca, "PCA_PORT"),
        }
        for name, (cls, attr) in port_attrs.items():
            saved[(cls, attr)] = getattr(cls, attr)
            setattr(cls, attr, str(ports[name]))
        saved_wait = lo.AsyncronousWait.WAIT_TIME
        lo.AsyncronousWait.WAIT_TIME = 0.1
        lo.Context("127.0.0.1")

        try:
            database = lo.DatabaseApi()
            assert database.create_file(
                "titanic", titanic_csv, pretty_response=False
            ) == {"result": "file_created"}

            projection_client = lo.Projection()
            fields = ["Survived", "Pclass", "Sex", "Age", "Fare"]
            assert projection_client.create_projection(
                "titanic", "proj", list(fields), pretty_response=False
            ) == {"result": "created_file"}

            handler = lo.DataTypeHandler()
            numeric = {f: "number" for f in ("Survived", "Pclass", "Age", "Fare")}
            assert handler.change_file_type(
                "proj", numeric, pretty_response=False
            ) == {"result": "file_changed"}

            histogram_client = lo.Histogram()
            assert histogram_client.create_histogram(
                "proj", "hist", ["Sex"], pretty_response=False
            ) == {"result": "created_file"}

            model = lo.Model()
            preprocessor = (
                "features_training = training_df\n"
                "features_testing = testing_df\n"
                "features_evaluation = None\n"
                "from pyspark.ml.feature import VectorAssembler\n"
                "assembler = VectorAssembler("
                "inputCols=['Pclass','Fare'], outputCol='features')\n"
                "features_training = assembler.transform("
                "features_training.na.fill(0).withColumn("
                "'label', features_training['Survived']))\n"
                "features_testing = assembler.transform("
                "features_testing.na.fill(0).withColumn("
                "'label', features_testing['Survived']))\n"
            )
            assert model.create_model(
                "proj", "proj", preprocessor, ["nb"], pretty_response=False
            ) == {"result": "created_file"}

            rows = database.read_file(
                "proj_prediction_nb", limit=5, pretty_response=False
            )["result"]
            assert rows[0]["classificator"] == "nb"
            assert "prediction" in rows[1]

            # tsne — the heaviest compile — must also serve in the
            # split topology (VERDICT round 2, weak item 8).
            tsne_client = lo.Tsne()
            assert tsne_client.create_image_plot(
                "tsne_proj", "proj", "Survived", pretty_response=False
            ) == {"result": "created_file"}
            listing = tsne_client.read_image_plot_filenames(
                pretty_response=False
            )
            assert "tsne_proj.png" in listing["result"]
        finally:
            for (cls, attr), value in saved.items():
                setattr(cls, attr, value)
            lo.AsyncronousWait.WAIT_TIME = saved_wait
    finally:
        for process in processes:
            process.terminate()
        for process in processes:
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                process.kill()
