"""Store-as-a-service: wire-protocol conformance of RemoteStore against
a live store server, and the full seven-processes-plus-store topology
(the reference's container layout, docker-compose.yml:173-330)."""

import json
import os
import subprocess
import sys
import time

import pytest

from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
    UnsupportedQueryError,
)
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    create_store_app,
)
from learningorchestra_tpu.utils.web import ServerThread

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def remote_store():
    server = ServerThread(create_store_app(InMemoryStore()), "127.0.0.1", 0).start()
    yield RemoteStore(f"http://127.0.0.1:{server.port}")
    server.stop()


class TestRemoteStoreConformance:
    def test_collection_lifecycle(self, remote_store):
        assert remote_store.create_collection("ds") is True
        assert remote_store.create_collection("ds") is False
        assert remote_store.list_collections() == ["ds"]
        remote_store.drop("ds")
        assert remote_store.list_collections() == []

    def test_documents_roundtrip(self, remote_store):
        remote_store.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
        remote_store.insert_many("ds", [{ROW_ID: 1, "a": "x"}, {ROW_ID: 2, "a": "y"}])
        assert remote_store.count("ds") == 3
        assert remote_store.find_one("ds", {"a": "y"}) == {ROW_ID: 2, "a": "y"}
        remote_store.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True})
        assert remote_store.is_finished("ds")

    def test_columnar_roundtrip(self, remote_store):
        remote_store.insert_columns("ds", {"a": ["1", "2", "3"], "b": [1.5, None, 3.0]})
        assert remote_store.read_columns("ds", ["a", "b", ROW_ID]) == {
            "a": ["1", "2", "3"],
            "b": [1.5, None, 3.0],
            ROW_ID: [1, 2, 3],
        }
        remote_store.set_column("ds", "a", [1, 2, 3])
        assert remote_store.read_columns("ds", ["a"]) == {"a": [1, 2, 3]}
        remote_store.set_field_values("ds", "b", {2: 9.0})
        assert remote_store.read_columns("ds", ["b"]) == {"b": [1.5, 9.0, 3.0]}

    def test_find_operators_and_pagination(self, remote_store):
        remote_store.insert_columns("ds", {"x": list(range(10))})
        docs = list(remote_store.find("ds", {"x": {"$gte": 5}}, skip=1, limit=2))
        assert [d["x"] for d in docs] == [6, 7]

    def test_read_columns_paged_on_wire(self, remote_store):
        """The read data plane travels in bounded chunks: with a tiny
        wire_rows the same columns come back from multiple small bodies,
        byte-identical to one big read."""
        remote_store.insert_columns(
            "ds", {"a": list(range(25)), "b": [str(i) for i in range(25)]}
        )
        calls = []
        original = remote_store._post

        def counting_post(path, payload):
            if path.endswith("/read_columns"):
                calls.append(payload)
            return original(path, payload)

        remote_store._post = counting_post
        try:
            remote_store.wire_rows = 7
            paged = remote_store.read_columns("ds", ["a", "b"])
            remote_store.wire_rows = 100000
            full = remote_store.read_columns("ds", ["a", "b"])
        finally:
            remote_store._post = original
        assert paged == full
        assert len(calls) >= 4  # 25 rows / 7 per chunk
        assert all(c["limit"] <= 7 for c in calls[:4])

    def test_read_columns_start_limit(self, remote_store):
        remote_store.insert_columns("ds", {"x": list(range(10))})
        assert remote_store.read_columns("ds", ["x"], start=3, limit=4) == {
            "x": [3, 4, 5, 6]
        }

    def test_degenerate_wire_rows_never_spins(self, remote_store):
        remote_store.insert_columns("ds", {"x": [1, 2]})
        remote_store.wire_rows = 0  # e.g. LO_WIRE_ROWS misconfigured
        assert remote_store.read_columns("ds", ["x"])["x"] == []

    def test_aggregate_group(self, remote_store):
        remote_store.insert_columns("ds", {"s": ["a", "b", "a"]})
        result = remote_store.aggregate(
            "ds", [{"$group": {"_id": "$s", "count": {"$sum": 1}}}]
        )
        assert {r["_id"]: r["count"] for r in result} == {"a": 2, "b": 1}

    def test_error_mapping(self, remote_store):
        remote_store.insert_one("ds", {ROW_ID: 1})
        with pytest.raises(KeyError):
            remote_store.insert_one("ds", {ROW_ID: 1})
        with pytest.raises(UnsupportedQueryError):
            list(remote_store.find("ds", {"a": {"$mod": [2, 0]}}))
        with pytest.raises(ValueError):
            remote_store.insert_columns("ds", {"a": [1], "b": [1, 2]})

    def test_services_run_against_remote_store(self, remote_store, titanic_csv):
        """The service layer is store-backend agnostic: the projection
        service works unchanged over the wire protocol."""
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
        from learningorchestra_tpu.services import projection

        write_ingest_metadata(remote_store, "titanic", titanic_csv)
        ingest_csv(remote_store, "titanic", titanic_csv)
        client = projection.create_app(remote_store).test_client()
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "proj", "fields": ["Name", "Age"]},
        )
        assert response.status_code == 201
        assert remote_store.is_finished("proj")
        assert remote_store.read_columns("proj", ["Name"])["Name"][0] == "Braund, Mr. Owen"


class TestReplication:
    """WAL-shipping HA: primary feeds /wal, follower tails it, serves
    reads, rejects writes, survives primary compaction (epoch resync),
    and takes over on POST /promote — the reference's Mongo replica-set
    role (docker-compose.yml:27-91) with promotion instead of election."""

    @pytest.fixture()
    def pair(self, tmp_path):
        from learningorchestra_tpu.core.store_service import (
            ReplicationClient,
            serve,
        )

        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            data_dir=str(tmp_path / "follower"),
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        # deterministic tests: stop the auto-poller, drive a fresh
        # (unstarted) client over the same store by hand
        follower.replication.stop()
        poller = ReplicationClient(
            follower.store, f"http://127.0.0.1:{primary.port}"
        )
        yield (
            RemoteStore(f"http://127.0.0.1:{primary.port}"),
            RemoteStore(f"http://127.0.0.1:{follower.port}"),
            poller,
            follower,
        )
        primary.stop()
        follower.stop()

    def _sync(self, poller):
        # first poll resolves the epoch (resync), then data flows
        for _ in range(5):
            poller.poll_once()

    def test_follower_catches_up_and_rejects_writes(self, pair):
        primary, follower, poller, _ = pair
        primary.insert_one("ds", {ROW_ID: METADATA_ID, "finished": False})
        primary.insert_columns("ds", {"a": [1, 2, 3]})
        primary.update_one("ds", {ROW_ID: METADATA_ID}, {"finished": True})
        self._sync(poller)
        assert follower.read_columns("ds", ["a"]) == {"a": [1, 2, 3]}
        assert follower.is_finished("ds")
        with pytest.raises(PermissionError):
            follower.insert_one("ds", {"a": 9})

    def test_epoch_resync_after_primary_compaction(self, tmp_path):
        """Compaction bumps the epoch; a follower mid-stream resyncs
        from the snapshot and converges on the post-compaction state."""
        from learningorchestra_tpu.core.store_service import (
            ReplicationClient,
            create_store_app,
        )

        primary_store = InMemoryStore(
            data_dir=str(tmp_path / "primary"), replicate=True
        )
        server = ServerThread(
            create_store_app(primary_store), "127.0.0.1", 0
        ).start()
        try:
            follower_store = InMemoryStore(replicate=True)
            poller = ReplicationClient(
                follower_store, f"http://127.0.0.1:{server.port}"
            )
            primary_store.insert_columns("ds", {"a": list(range(6))})
            self._sync(poller)
            assert follower_store.read_columns("ds", ["a"])["a"] == list(
                range(6)
            )
            primary_store.insert_one("ds", {"a": 6})
            primary_store.compact()  # epoch 0 -> 1; old offset now invalid
            primary_store.insert_one("ds", {"a": 7})
            self._sync(poller)
            assert poller.epoch == 1
            values = follower_store.read_columns("ds", ["a"])["a"]
            assert 6 in values and 7 in values and len(values) == 8
        finally:
            server.stop()

    def test_epoch_survives_primary_restart(self, tmp_path):
        """The epoch lives IN the log: a compacted-then-rebooted primary
        must not reissue its pre-compaction epoch, or stale follower
        cursors would validate against the rewritten log."""
        data_dir = str(tmp_path / "p")
        store = InMemoryStore(data_dir=data_dir, replicate=True)
        store.insert_columns("ds", {"a": [1, 2]})
        store.compact()
        assert store._wal_epoch == 1
        reopened = InMemoryStore(data_dir=data_dir, replicate=True)
        assert reopened._wal_epoch == 1
        assert reopened.read_columns("ds", ["a"]) == {"a": [1, 2]}

    def test_resync_never_leaves_follower_empty(self, tmp_path):
        """resync_apply replaces the durable WAL atomically WITH the new
        records — a follower that crashes right after a resync reopens
        with the snapshot state, never with nothing."""
        data_dir = str(tmp_path / "f")
        follower = InMemoryStore(data_dir=data_dir, replicate=True)
        follower.insert_columns("old", {"x": [1]})
        lines = [
            json.dumps({"op": "create", "c": "fresh"}),
            json.dumps({"op": "insert_cols", "c": "fresh", "s": 1,
                        "d": {"a": [10, 11]}}),
        ]
        follower.resync_apply(lines)
        assert follower.list_collections() == ["fresh"]
        # simulate crash: reopen from disk alone
        reopened = InMemoryStore(data_dir=data_dir)
        assert reopened.list_collections() == ["fresh"]
        assert reopened.read_columns("fresh", ["a"]) == {"a": [10, 11]}

    def test_promote_enables_writes(self, pair):
        primary, follower, poller, follower_server = pair
        primary.insert_columns("ds", {"a": [1]})
        self._sync(poller)
        import requests as _requests

        response = _requests.post(follower.base_url + "/promote")
        assert response.json()["promoted"] is True
        follower.insert_one("ds", {"a": 2})  # no PermissionError
        assert follower.count("ds") == 2
        assert _requests.get(follower.base_url + "/health").json()[
            "writable"
        ] is True


def _spawn(env_extra, *argv):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    # services must come up fast and CPU-only in tests
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, *argv],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
    )


def _wait_port_line(process, marker, timeout=120):
    """Read stdout until the bring-up line appears; returns the line."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = process.stdout.readline()
        if not line:
            if process.poll() is not None:
                raise RuntimeError(f"process died (rc={process.returncode})")
            time.sleep(0.05)
            continue
        if marker in line:
            return line.strip()
    raise TimeoutError(f"no {marker!r} line within {timeout}s")


@pytest.mark.integration
def test_multiprocess_stack_titanic(tmp_path, titanic_csv):
    """Every service in its own OS process against one store server —
    the reference's deployment topology, driven by the unchanged client
    (VERDICT round 1, next-round item 3)."""
    import learningorchestra_tpu.client as lo

    processes = []
    try:
        store_proc = _spawn(
            {"LO_STORE_PORT": "0", "LO_DATA_DIR": str(tmp_path / "store")},
            "-m",
            "learningorchestra_tpu.core.store_service",
        )
        processes.append(store_proc)
        line = _wait_port_line(store_proc, "store server on ")
        store_port = int(line.split("store server on ")[1].split()[0].rsplit(":", 1)[1])
        store_url = f"http://127.0.0.1:{store_port}"

        ports = {}
        for name in (
            "database_api",
            "projection",
            "model_builder",
            "data_type_handler",
            "histogram",
            "tsne",
            "pca",
        ):
            proc = _spawn(
                {
                    "LO_SERVICE": name,
                    "LO_PORT": "0",
                    "LO_STORE_URL": store_url,
                    "LO_IMAGES_DIR": str(tmp_path / "images"),
                },
                "-m",
                "learningorchestra_tpu.services.runner",
            )
            processes.append(proc)
            line = _wait_port_line(proc, f"service {name} on ")
            ports[name] = int(line.rsplit(":", 1)[1])

        saved = {}
        port_attrs = {
            "database_api": (lo.DatabaseApi, "DATABASE_API_PORT"),
            "projection": (lo.Projection, "PROJECTION_PORT"),
            "model_builder": (lo.Model, "MODEL_BUILDER_PORT"),
            "data_type_handler": (lo.DataTypeHandler, "DATA_TYPE_HANDLER_PORT"),
            "histogram": (lo.Histogram, "HISTOGRAM_PORT"),
            "tsne": (lo.Tsne, "TSNE_PORT"),
            "pca": (lo.Pca, "PCA_PORT"),
        }
        for name, (cls, attr) in port_attrs.items():
            saved[(cls, attr)] = getattr(cls, attr)
            setattr(cls, attr, str(ports[name]))
        saved_wait = lo.AsyncronousWait.WAIT_TIME
        lo.AsyncronousWait.WAIT_TIME = 0.1
        lo.Context("127.0.0.1")

        try:
            database = lo.DatabaseApi()
            assert database.create_file(
                "titanic", titanic_csv, pretty_response=False
            ) == {"result": "file_created"}

            projection_client = lo.Projection()
            fields = ["Survived", "Pclass", "Sex", "Age", "Fare"]
            assert projection_client.create_projection(
                "titanic", "proj", list(fields), pretty_response=False
            ) == {"result": "created_file"}

            handler = lo.DataTypeHandler()
            numeric = {f: "number" for f in ("Survived", "Pclass", "Age", "Fare")}
            assert handler.change_file_type(
                "proj", numeric, pretty_response=False
            ) == {"result": "file_changed"}

            histogram_client = lo.Histogram()
            assert histogram_client.create_histogram(
                "proj", "hist", ["Sex"], pretty_response=False
            ) == {"result": "created_file"}

            model = lo.Model()
            preprocessor = (
                "features_training = training_df\n"
                "features_testing = testing_df\n"
                "features_evaluation = None\n"
                "from pyspark.ml.feature import VectorAssembler\n"
                "assembler = VectorAssembler("
                "inputCols=['Pclass','Fare'], outputCol='features')\n"
                "features_training = assembler.transform("
                "features_training.na.fill(0).withColumn("
                "'label', features_training['Survived']))\n"
                "features_testing = assembler.transform("
                "features_testing.na.fill(0).withColumn("
                "'label', features_testing['Survived']))\n"
            )
            assert model.create_model(
                "proj", "proj", preprocessor, ["nb"], pretty_response=False
            ) == {"result": "created_file"}

            rows = database.read_file(
                "proj_prediction_nb", limit=5, pretty_response=False
            )["result"]
            assert rows[0]["classificator"] == "nb"
            assert "prediction" in rows[1]

            # tsne — the heaviest compile — must also serve in the
            # split topology (VERDICT round 2, weak item 8).
            tsne_client = lo.Tsne()
            assert tsne_client.create_image_plot(
                "tsne_proj", "proj", "Survived", pretty_response=False
            ) == {"result": "created_file"}
            listing = tsne_client.read_image_plot_filenames(
                pretty_response=False
            )
            assert "tsne_proj.png" in listing["result"]
        finally:
            for (cls, attr), value in saved.items():
                setattr(cls, attr, value)
            lo.AsyncronousWait.WAIT_TIME = saved_wait
    finally:
        for process in processes:
            process.terminate()
        for process in processes:
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                process.kill()


class TestAutoFailover:
    """Election-analogue failover (VERDICT r4 missing #3): a follower
    with LO_AUTO_PROMOTE_S self-promotes when its primary dies, a
    multi-URL RemoteStore re-points writes at the survivor, and a
    revived old primary is fenced by the promotion's term bump —
    the roles Mongo's replica-set election + arbiter play in the
    reference (docker-compose.yml:49-91)."""

    def _wait_for(self, predicate, timeout=15.0, message="condition"):
        deadline = time.time() + timeout
        while time.time() < deadline:
            if predicate():
                return
            time.sleep(0.1)
        raise AssertionError(f"timed out waiting for {message}")

    def test_kill_primary_then_writes_resume_unattended(self):
        from learningorchestra_tpu.core.store_service import serve

        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
            auto_promote_s=0.5,
        )
        try:
            client = RemoteStore(
                f"http://127.0.0.1:{primary.port},"
                f"http://127.0.0.1:{follower.port}",
                failover_timeout=20,
            )
            client.create_collection("ds")
            # explicit ids: only explicit-id inserts retry across a
            # failover (an auto-id replay could duplicate the row)
            client.insert_one("ds", {"_id": 10, "a": 1})
            self._wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )
            primary.stop()  # no operator action from here on
            client.insert_one("ds", {"_id": 11, "a": 2})  # rides the takeover
            assert follower.store_role["writable"]
            assert follower.store_role["term"] == 2
            values = [
                d["a"] for d in follower.store.find("ds", {})
            ]
            assert sorted(values) == [1, 2]
        finally:
            primary.stop()
            follower.stop()

    def test_promote_response_reports_term_and_catchup(self):
        import requests as rq

        from learningorchestra_tpu.core.store_service import serve

        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            primary_store = RemoteStore(f"http://127.0.0.1:{primary.port}")
            primary_store.create_collection("ds")
            primary_store.insert_one("ds", {"a": 1})
            self._wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )
            response = rq.post(
                f"http://127.0.0.1:{follower.port}/promote", timeout=10
            )
            payload = response.json()
            assert payload["promoted"] is True
            assert payload["term"] == 2
            assert payload["caught_up"] is True
            assert payload["applied_through"]["offset"] > 0
            # idempotent: a second promote neither bumps the term nor fails
            again = rq.post(
                f"http://127.0.0.1:{follower.port}/promote", timeout=10
            ).json()
            assert again["term"] == 2
        finally:
            primary.stop()
            follower.stop()

    def test_revived_old_primary_rejoins_as_follower(self):
        import requests as rq

        from learningorchestra_tpu.core.store_service import serve

        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            old_port = primary.port
            store_client = RemoteStore(f"http://127.0.0.1:{old_port}")
            store_client.create_collection("ds")
            store_client.insert_one("ds", {"a": 1})
            self._wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )
            primary.stop()
            rq.post(f"http://127.0.0.1:{follower.port}/promote", timeout=10)
            new_primary = RemoteStore(f"http://127.0.0.1:{follower.port}")
            new_primary.insert_one("ds", {"a": 2})  # diverges from old
            # The old primary revives pointing at its peer list — and
            # must come back as a FOLLOWER of the promoted server, with
            # the post-failover writes resynced over its stale state.
            revived = serve(
                "127.0.0.1",
                old_port,
                replicate=True,
                peers=[f"http://127.0.0.1:{follower.port}"],
            )
            try:
                assert revived.store_role["writable"] is False
                with pytest.raises(PermissionError):
                    RemoteStore(f"http://127.0.0.1:{old_port}").insert_one(
                        "ds", {"a": 99}
                    )
                self._wait_for(
                    lambda: revived.store.count("ds") == 2,
                    message="revived resync",
                )
            finally:
                revived.stop()
        finally:
            primary.stop()
            follower.stop()

    def test_live_stale_primary_fenced_by_higher_term_peer(self):
        import requests as rq

        from learningorchestra_tpu.core.store_service import serve

        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            # wire the primary's fencing probe AFTER the follower exists
            # (serve() probes at startup too; here we exercise the
            # ongoing monitor path: a partition heals and the old
            # primary finds itself superseded)
            primary.stop()
            partitioned = serve(
                "127.0.0.1",
                primary.port,
                replicate=True,
                peers=[f"http://127.0.0.1:{follower.port}"],
            )
            try:
                # takeover happens while the old primary is "partitioned
                # away" (here: before it notices)
                rq.post(
                    f"http://127.0.0.1:{follower.port}/promote", timeout=10
                )
                self._wait_for(
                    lambda: partitioned.store_role["writable"] is False,
                    message="stale primary demotion",
                )
            finally:
                partitioned.stop()
        finally:
            primary.stop()
            follower.stop()

    def test_both_followers_deadlock_self_heals(self):
        """After a failover, a supervisor restart of the promoted server
        (original env) can leave BOTH nodes followers of each other —
        every /wal poll succeeds, so plain unreachability timers never
        fire. A follower must also treat a reachable-but-unwritable
        primary as down; both sides then promote and the term/boot
        fence converges on exactly one writer."""
        from learningorchestra_tpu.core.store_service import serve

        a = serve("127.0.0.1", 0, replicate=True)  # no takeover timer:
        # the test pins WHICH side must win (with timers on both, either
        # may promote first and the fence settles it — nondeterministic)
        b_port_probe = None
        try:
            # B follows A; A is then demoted BY HAND to simulate the
            # post-restart swap state (A follower of B, B follower of A)
            b = serve(
                "127.0.0.1",
                0,
                primary_url=f"http://127.0.0.1:{a.port}",
                peers=[f"http://127.0.0.1:{a.port}"],
                auto_promote_s=0.5,
            )
            try:
                from learningorchestra_tpu.core.store_service import (
                    ReplicationClient,
                )

                with a.store_role["lock"]:
                    a.store_role["writable"] = False
                    a.store_role["poller"] = ReplicationClient(
                        a.store, f"http://127.0.0.1:{b.port}"
                    ).start()
                # Only B runs a takeover monitor here (A's serve() was
                # writable so its monitor watches peers, not a poller) —
                # B must detect its (unwritable) primary and promote.
                deadline = time.time() + 15
                while time.time() < deadline:
                    if b.store_role["writable"]:
                        break
                    time.sleep(0.2)
                assert b.store_role["writable"], (
                    "follower never promoted past an unwritable primary"
                )
            finally:
                b.stop()
        finally:
            a.stop()
            del b_port_probe


class TestFailoverLandedWrites:
    """An explicit-id write that LANDED before the primary died must not
    fail the client when the failover retry answers duplicate-id
    (ADVICE r5: long ingests used to die mid-batch on exactly this)."""

    class _R409:
        status_code = 409

        def json(self):
            return {"error": "duplicate _id values [1, 2]"}

    def _flaky_send(self):
        import requests

        calls = []

        def send(base):
            calls.append(base)
            if len(calls) == 1:
                raise requests.ConnectionError("primary died mid-write")
            return self._R409()

        return send

    def _patched(self, monkeypatch):
        from learningorchestra_tpu.core import store_service

        monkeypatch.setattr(
            store_service,
            "probe_health",
            lambda url, timeout=2.0: {
                "ok": True,
                "writable": url == "http://b",
            },
        )
        return store_service

    def test_duplicate_after_ambiguous_retry_is_success(self, monkeypatch):
        store_service = self._patched(monkeypatch)
        store = store_service.RemoteStore(
            "http://a,http://b", failover_timeout=5
        )
        response = store._send(self._flaky_send(), retry=True, landed_ok=True)
        assert response.status_code == 409  # swallowed: the write landed

    def test_without_landed_ok_duplicate_still_raises(self, monkeypatch):
        store_service = self._patched(monkeypatch)
        store = store_service.RemoteStore(
            "http://a,http://b", failover_timeout=5
        )
        with pytest.raises(KeyError):
            store._send(self._flaky_send(), retry=True, landed_ok=False)

    def test_clean_first_attempt_409_still_raises(self, monkeypatch):
        # no ambiguity: a 409 on a healthy first attempt is a genuine
        # duplicate even for landed_ok calls
        store_service = self._patched(monkeypatch)
        store = store_service.RemoteStore("http://a", failover_timeout=5)
        with pytest.raises(KeyError):
            store._send(
                lambda base: self._R409(), retry=True, landed_ok=True
            )

    def test_5xx_is_ambiguous_like_a_dropped_connection(self, monkeypatch):
        """A 500 mid-request (handler died after maybe applying) must
        ride the same landed-ok retry as a connection death."""

        class _R500:
            status_code = 500
            reason = "boom"
            url = "http://a"

            def raise_for_status(self):
                import requests as rq

                raise rq.HTTPError("500", response=self)

            def json(self):
                return {}

        store_service = self._patched(monkeypatch)
        store = store_service.RemoteStore(
            "http://a,http://b", failover_timeout=5
        )
        calls = []

        def send(base):
            calls.append(base)
            return _R500() if len(calls) == 1 else self._R409()

        response = store._send(send, retry=True, landed_ok=True)
        assert response.status_code == 409  # swallowed: the write landed


class TestCrossCallLandedWrites:
    """The residual ADVICE-r5 hole: the ambiguous failure and the
    duplicate-id 409 happen in DIFFERENT _send calls — the write landed
    on the dying primary, the client's op-level error propagated, and a
    higher-level retry (the scheduler re-running the ingest) replays
    it. The replay's clean-attempt 409 must verify by read and succeed
    instead of aborting a fully durable ingest."""

    @pytest.fixture()
    def live(self):
        from learningorchestra_tpu.core.store_service import (
            RemoteStore,
            create_store_app,
        )

        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        yield RemoteStore(f"http://127.0.0.1:{server.port}"), server
        server.stop()

    def _fail_next_post_ambiguously(self, store, monkeypatch):
        """Make exactly one _session.post die AFTER the server applied
        the write — the landed-but-unacked shape."""
        import requests as rq

        real_session = store._session
        state = {"armed": True}
        real_post = real_session.post

        def flaky_post(url, **kwargs):
            response = real_post(url, **kwargs)
            if state["armed"]:
                state["armed"] = False
                raise rq.ConnectionError("died after the server applied")
            return response

        monkeypatch.setattr(real_session, "post", flaky_post)

    def test_scheduler_style_replay_of_landed_insert_succeeds(
        self, live, monkeypatch
    ):
        store, _ = live
        self._fail_next_post_ambiguously(store, monkeypatch)
        with pytest.raises(Exception):  # the op-level failure the
            store.insert_one("ds", {ROW_ID: 1, "v": "x"})  # sched sees
        # the sched-level retry replays the op; the row is already
        # durable server-side, and the replay must treat it as landed
        store.insert_one("ds", {ROW_ID: 1, "v": "x"})
        assert store.count("ds") == 1

    def test_replay_with_different_content_still_raises(
        self, live, monkeypatch
    ):
        store, _ = live
        self._fail_next_post_ambiguously(store, monkeypatch)
        with pytest.raises(Exception):
            store.insert_one("ds", {ROW_ID: 1, "v": "x"})
        # same id, DIFFERENT content: a genuine conflict, not a replay
        with pytest.raises(KeyError):
            store.insert_one("ds", {ROW_ID: 1, "v": "different"})

    def test_replay_of_landed_column_chunk_succeeds(
        self, live, monkeypatch
    ):
        store, _ = live
        self._fail_next_post_ambiguously(store, monkeypatch)
        columns = {"a": [1.0, 2.0, 3.0], "b": ["x", "y", "z"]}
        with pytest.raises(Exception):
            store.insert_columns("ds", columns, start_id=1)
        store.insert_columns("ds", columns, start_id=1)  # the replay
        assert store.read_columns("ds", ["a"])["a"] == [1.0, 2.0, 3.0]

    def test_unmarked_collection_keeps_duplicate_semantics(self, live):
        store, _ = live
        store.insert_one("ds", {ROW_ID: 1, "v": "x"})
        # no ambiguity ever happened on this client: identical replay
        # is still a duplicate — the verify path only opens after an
        # ambiguous failure on the same collection
        with pytest.raises(KeyError):
            store.insert_one("ds", {ROW_ID: 1, "v": "x"})
