"""End-to-end model builder: Titanic-like data through the documented
preprocessor into all five classifiers."""

import numpy as np
import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.store import ROW_ID
from learningorchestra_tpu.ml.builder import build_model
from learningorchestra_tpu.ops.dtype import convert_field_types
from tests.test_frame import DOCUMENTED_PREPROCESSOR

NUMERIC_FIELDS = ("PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare")


@pytest.fixture()
def titanic_store(store, titanic_csv):
    for name in ("titanic_train", "titanic_test"):
        write_ingest_metadata(store, name, titanic_csv)
        ingest_csv(store, name, titanic_csv)
        convert_field_types(store, name, {f: "number" for f in NUMERIC_FIELDS})
    return store


class TestBuildModel:
    def test_lr_and_nb(self, titanic_store):
        results = build_model(
            titanic_store,
            "titanic_train",
            "titanic_test",
            DOCUMENTED_PREPROCESSOR,
            ["lr", "nb"],
        )
        assert {r["classificator"] for r in results} == {"lr", "nb"}
        for result in results:
            name = result["filename"]
            assert name.startswith("titanic_test_prediction_")
            meta = titanic_store.find_one(name, {ROW_ID: 0})
            assert meta["fit_time"] > 0
            assert "F1" in meta and isinstance(meta["F1"], str)
            assert "accuracy" in meta and isinstance(meta["accuracy"], str)
            rows = [
                d
                for d in titanic_store.find(name)
                if d[ROW_ID] != 0
            ]
            assert len(rows) == 8
            assert "prediction" in rows[0]
            assert isinstance(rows[0]["probability"], list)
            assert "features" not in rows[0]

    def test_invalid_classifier_raises(self, titanic_store):
        with pytest.raises(KeyError):
            build_model(
                titanic_store,
                "titanic_train",
                "titanic_test",
                DOCUMENTED_PREPROCESSOR,
                ["svm"],
            )

    def test_no_evaluation_split(self, titanic_store):
        code = DOCUMENTED_PREPROCESSOR.replace(
            "(features_training, features_evaluation) =\\\n"
            "    features_training.randomSplit([0.8, 0.2], seed=33)",
            "features_evaluation = None",
        )
        results = build_model(
            titanic_store, "titanic_train", "titanic_test", code, ["nb"]
        )
        assert "F1" not in results[0]
