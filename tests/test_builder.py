"""End-to-end model builder: Titanic-like data through the documented
preprocessor into all five classifiers."""

import numpy as np
import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.store import ROW_ID
from learningorchestra_tpu.ml.builder import build_model
from learningorchestra_tpu.ops.dtype import convert_field_types
from tests.test_frame import DOCUMENTED_PREPROCESSOR

NUMERIC_FIELDS = ("PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare")


@pytest.fixture()
def titanic_store(store, titanic_csv):
    for name in ("titanic_train", "titanic_test"):
        write_ingest_metadata(store, name, titanic_csv)
        ingest_csv(store, name, titanic_csv)
        convert_field_types(store, name, {f: "number" for f in NUMERIC_FIELDS})
    return store


class TestBuildModel:
    def test_lr_and_nb(self, titanic_store):
        results = build_model(
            titanic_store,
            "titanic_train",
            "titanic_test",
            DOCUMENTED_PREPROCESSOR,
            ["lr", "nb"],
        )
        assert {r["classificator"] for r in results} == {"lr", "nb"}
        for result in results:
            name = result["filename"]
            assert name.startswith("titanic_test_prediction_")
            meta = titanic_store.find_one(name, {ROW_ID: 0})
            assert meta["fit_time"] > 0
            assert "F1" in meta and isinstance(meta["F1"], str)
            assert "accuracy" in meta and isinstance(meta["accuracy"], str)
            rows = [
                d
                for d in titanic_store.find(name)
                if d[ROW_ID] != 0
            ]
            assert len(rows) == 8
            assert "prediction" in rows[0]
            assert isinstance(rows[0]["probability"], list)
            assert "features" not in rows[0]

    def test_two_warm_builds_complete_concurrently(self, titanic_store):
        """Regression for the PR 8 KNOWN LATENT: on the 8-virtual-device
        CPU backend, two warm builds running their collective evals
        concurrently used to deadlock XLA's CPU rendezvous (each
        program's participants holding part of the host thread pool,
        waiting on peers the other program occupies). The
        _collective_dispatch_guard in ml/builder.py now serializes
        those dispatches on single-process CPU, so two concurrent
        builds must COMPLETE — and agree with each other."""
        import threading

        # warm build: compiles every program so the concurrent pair
        # below executes already-compiled collectives (the deadlock's
        # trigger condition)
        build_model(
            titanic_store,
            "titanic_train",
            "titanic_test",
            DOCUMENTED_PREPROCESSOR,
            ["lr", "nb", "dt"],
        )
        results: dict = {}

        def run(slot: str) -> None:
            try:
                results[slot] = build_model(
                    titanic_store,
                    "titanic_train",
                    "titanic_test",
                    DOCUMENTED_PREPROCESSOR,
                    ["lr", "nb", "dt"],
                    # the second build writes to a distinct prediction
                    # namespace only through timing; writing outputs
                    # from both is fine (same collections, drop+insert)
                )
            except BaseException as error:  # noqa: BLE001 — asserted below
                results[slot] = error

        threads = [
            threading.Thread(target=run, args=(slot,), daemon=True)
            for slot in ("a", "b")
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            # generous bound: a deadlock parks forever, a healthy pair
            # of warm 3-classifier builds takes seconds
            thread.join(timeout=300)
        assert not any(t.is_alive() for t in threads), (
            "concurrent warm builds did not complete — the CPU "
            "rendezvous guard regressed"
        )
        for slot in ("a", "b"):
            assert not isinstance(results[slot], BaseException), results[slot]
            assert {r["classificator"] for r in results[slot]} == {
                "lr",
                "nb",
                "dt",
            }

    def test_invalid_classifier_raises(self, titanic_store):
        with pytest.raises(KeyError):
            build_model(
                titanic_store,
                "titanic_train",
                "titanic_test",
                DOCUMENTED_PREPROCESSOR,
                ["svm"],
            )

    def test_no_evaluation_split(self, titanic_store):
        code = DOCUMENTED_PREPROCESSOR.replace(
            "(features_training, features_evaluation) =\\\n"
            "    features_training.randomSplit([0.8, 0.2], seed=33)",
            "features_evaluation = None",
        )
        results = build_model(
            titanic_store, "titanic_train", "titanic_test", code, ["nb"]
        )
        assert "F1" not in results[0]


class TestFusedEvaluatePredict:
    """ml/base.evaluate_predict: metrics + predictions in ONE device→host
    transfer, sharing the forward pass when eval and test frames alias
    (the VERDICT-r4 evaluate/predict tail collapse)."""

    def _fit_nb(self, rows=256):
        import numpy as np

        from learningorchestra_tpu.ml.naive_bayes import NaiveBayes

        rng = np.random.default_rng(3)
        X = rng.random((rows, 6)).astype(np.float32)
        y = (X[:, 0] > 0.5).astype(np.int32)
        return NaiveBayes().fit(X, y), X, y

    def test_matches_separate_calls(self):
        import numpy as np

        from learningorchestra_tpu.ml.base import shard_labels, shard_matrix

        model, X, y = self._fit_nb()
        Xd = shard_matrix(X)
        yd = shard_labels(y)
        accuracy, f1, labels, probs = model.evaluate_predict(Xd, yd, Xd)
        sep_accuracy, sep_f1 = model.evaluate(Xd, yd)
        sep_labels, sep_probs = model.predict_both(Xd)
        assert accuracy == sep_accuracy and f1 == sep_f1
        np.testing.assert_array_equal(labels, sep_labels)
        np.testing.assert_allclose(probs, sep_probs)
        assert len(labels) == len(X)  # padding cropped

    def test_distinct_test_frame(self):
        import numpy as np

        from learningorchestra_tpu.ml.base import shard_labels, shard_matrix

        model, X, y = self._fit_nb()
        X_test = X[:100] * 0.5  # different content AND row count
        Xd_eval = shard_matrix(X)
        Xd_test = shard_matrix(X_test)
        yd = shard_labels(y)
        accuracy, _, labels, probs = model.evaluate_predict(
            Xd_eval, yd, Xd_test
        )
        assert len(labels) == len(probs) == 100
        sep_labels, _ = model.predict_both(Xd_test)
        np.testing.assert_array_equal(labels, sep_labels)
        assert accuracy == model.evaluate(Xd_eval, yd)[0]

    def test_alias_if_equal_aliases_only_equal_frames(self):
        import numpy as np

        from learningorchestra_tpu.frame.dataframe import DataFrame
        from learningorchestra_tpu.ml.builder import _alias_if_equal

        X = np.arange(12, dtype=np.float64).reshape(4, 3)
        base = {
            "features": X,
            "label": np.array([0.0, 1.0, 0.0, 1.0]),
        }
        testing = DataFrame(dict(base))
        equal = DataFrame({"features": X.copy(), "label": base["label"].copy()})
        different = DataFrame(
            {"features": X + 1, "label": base["label"].copy()}
        )
        assert _alias_if_equal(equal, testing) is testing
        assert _alias_if_equal(different, testing) is different
        assert _alias_if_equal(None, testing) is None
