"""Ops: projection, dtype conversion, histogram — store-level contracts."""

import numpy as np
import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID
from learningorchestra_tpu.ops import (
    convert_field_types,
    create_histogram,
    project,
    value_counts,
)


@pytest.fixture()
def ingested(store, titanic_csv):
    write_ingest_metadata(store, "titanic", titanic_csv)
    ingest_csv(store, "titanic", titanic_csv)
    return store


class TestProjection:
    def test_projects_fields_and_preserves_ids(self, ingested):
        n = project(ingested, "titanic", "proj", ["Name", "Age"])
        assert n == 8
        rows = [
            d for d in ingested.find("proj") if d[ROW_ID] != METADATA_ID
        ]
        assert len(rows) == 8
        assert set(rows[0].keys()) == {"Name", "Age", ROW_ID}
        assert [r[ROW_ID] for r in rows] == list(range(1, 9))

    def test_metadata_contract(self, ingested):
        project(ingested, "titanic", "proj", ["Sex"])
        meta = ingested.metadata("proj")
        assert meta["finished"] is True
        assert meta["parent_filename"] == "titanic"
        assert meta["filename"] == "proj"
        assert meta["fields"] == ["Sex"]

    def test_id_in_fields_not_duplicated(self, ingested):
        # the reference client appends _id to the field list itself
        project(ingested, "titanic", "proj", ["Sex", ROW_ID])
        meta = ingested.metadata("proj")
        assert meta["fields"] == ["Sex"]


class TestDtype:
    def test_string_to_number(self, ingested):
        convert_field_types(ingested, "titanic", {"Age": "number", "Fare": "number"})
        rows = list(ingested.find("titanic", {ROW_ID: 1}))
        assert rows[0]["Age"] == 22
        assert isinstance(rows[0]["Age"], int)
        assert rows[0]["Fare"] == 7.25

    def test_empty_string_becomes_none(self, ingested):
        convert_field_types(ingested, "titanic", {"Age": "number"})
        row = next(ingested.find("titanic", {ROW_ID: 6}))
        assert row["Age"] is None

    def test_number_back_to_string(self, ingested):
        convert_field_types(ingested, "titanic", {"Age": "number"})
        convert_field_types(ingested, "titanic", {"Age": "string"})
        row = next(ingested.find("titanic", {ROW_ID: 1}))
        assert row["Age"] == "22"
        row = next(ingested.find("titanic", {ROW_ID: 6}))
        assert row["Age"] == ""

    def test_invalid_number_raises(self, ingested):
        with pytest.raises(ValueError):
            convert_field_types(ingested, "titanic", {"Name": "number"})

    def test_invalid_type_name_raises(self, ingested):
        with pytest.raises(ValueError):
            convert_field_types(ingested, "titanic", {"Age": "boolean"})


class TestValueCounts:
    def test_string_counts(self):
        pairs = value_counts(["S", "C", "S", "Q", "S"])
        assert dict(pairs) == {"S": 3, "C": 1, "Q": 1}

    def test_numeric_counts_on_device(self):
        pairs = value_counts([3, 1, 3, 3.0, 2.5])
        assert dict(pairs) == {3: 3, 1: 1, 2.5: 1}

    def test_nulls_grouped(self):
        pairs = value_counts([None, 1.0, float("nan"), 1])
        assert dict(pairs) == {1: 2, None: 2}

    def test_large_column_matches_numpy(self, rng):
        data = rng.integers(0, 50, size=10_000).astype(float)
        expected_values, expected_counts = np.unique(data, return_counts=True)
        pairs = value_counts(list(data))
        assert [p[0] for p in pairs] == [int(v) for v in expected_values]
        assert [p[1] for p in pairs] == list(expected_counts)


class TestHistogram:
    def test_document_shape(self, ingested):
        create_histogram(ingested, "titanic", "hist", ["Sex", "Pclass"])
        meta = ingested.metadata("hist")
        assert meta["filename_parent"] == "titanic"
        assert meta["fields"] == ["Sex", "Pclass"]
        doc1 = next(ingested.find("hist", {ROW_ID: 1}))
        counts = {entry["_id"]: entry["count"] for entry in doc1["Sex"]}
        assert counts == {"male": 5, "female": 3}
        doc2 = next(ingested.find("hist", {ROW_ID: 2}))
        assert {e["_id"] for e in doc2["Pclass"]} == {"1", "3"}


class TestReviewRegressions:
    def test_projection_missing_field_raises(self, ingested):
        with pytest.raises(KeyError):
            project(ingested, "titanic", "proj", ["Agee"])
        # metadata was never marked finished with bogus rows
        meta = ingested.metadata("proj")
        assert meta is None or not meta.get("finished")

    def test_value_counts_mixed_unorderable_types(self):
        pairs = value_counts(["a", True, "a", None])
        assert dict(pairs) == {"a": 2, True: 1, None: 1}

    def test_wal_set_field_preserves_id_types(self, tmp_path):
        from learningorchestra_tpu.core.store import InMemoryStore

        store = InMemoryStore(data_dir=str(tmp_path))
        store.insert_one("c", {ROW_ID: 1, "x": "a"})
        store.insert_one("c", {ROW_ID: "7", "x": "b"})
        store.set_field_values("c", "x", {1: "A", "7": "B"})
        reopened = InMemoryStore(data_dir=str(tmp_path))
        assert next(reopened.find("c", {ROW_ID: 1}))["x"] == "A"
        assert next(reopened.find("c", {ROW_ID: "7"}))["x"] == "B"


class TestDtypeVectorizedParity:
    """The vectorized converters must match the per-value reference
    converters exactly, including the grammar/overflow edges the review
    flagged."""

    def _roundtrip(self, store_factory, values, target):
        from learningorchestra_tpu.core.store import InMemoryStore

        store = InMemoryStore()
        store.insert_one("ds", {"_id": 0, "finished": True, "fields": ["x"]})
        store.insert_columns("ds", {"x": values})
        convert_field_types(store, "ds", {"x": target})
        return store.read_columns("ds", ["x"])["x"]

    def test_huge_integral_float_to_string(self):
        from learningorchestra_tpu.ops.dtype import _to_string

        values = [1e19, 2.5, 28.0]
        out = self._roundtrip(None, values, "string")
        assert out == [_to_string(v) for v in values]
        assert out[0] == "10000000000000000000"

    def test_number_conversion_int_collapse(self):
        out = self._roundtrip(None, ["28", "2.5", ""], "number")
        assert out == [28, 2.5, None]
        assert type(out[0]) is int and type(out[1]) is float

    def test_underscore_grammar_matches_python_float(self):
        # Python float() accepts "1_0"; numpy's parser rejects it — the
        # fallback loop must keep Python semantics
        out = self._roundtrip(None, ["1_0", "2"], "number")
        assert out == [10, 2]

    def test_bad_string_raises_value_error(self):
        import pytest as _pytest

        with _pytest.raises(ValueError):
            self._roundtrip(None, ["abc", "2"], "number")


def test_nan_cell_with_empty_cells_reads_back_null():
    # regression: a literal "nan" cell in a column that ALSO has ""
    # cells kept raw NaN (invalid JSON on the wire) instead of null
    from learningorchestra_tpu.core.store import InMemoryStore
    from learningorchestra_tpu.ops.dtype import convert_field_types

    store = InMemoryStore()
    store.create_collection("d")
    store.insert_one(
        "d", {"_id": 0, "filename": "d", "finished": True, "fields": ["a"]}
    )
    store.insert_columns("d", {"a": ["28", "2.5", "", "1_0", "nan"]})
    convert_field_types(store, "d", {"a": "number"})
    rows = [store.find_one("d", {"_id": i})["a"] for i in range(1, 6)]
    assert rows == [28, 2.5, None, 10, None]
    assert type(rows[0]) is int and type(rows[1]) is float
