"""utils/jitcache counters: hits/misses/compile-seconds bookkeeping.

VERDICT r4 flagged that a 1550 s compile-bound run could not be
diagnosed from its artifact because nothing recorded cache hits vs
misses; these stats are that diagnosis, so they get direct unit
coverage — the listener callbacks, the rounding contract of
``cache_stats()``, and the idempotence of listener registration.
"""

from __future__ import annotations

import pytest

from learningorchestra_tpu.utils import jitcache


@pytest.fixture()
def fresh_stats(monkeypatch):
    stats = {
        "persistent_cache_hits": 0,
        "persistent_cache_misses": 0,
        "backend_compile_s": 0.0,
        "trace_s": 0.0,
    }
    monkeypatch.setattr(jitcache, "_STATS", stats)
    return stats


class TestEventCounters:
    def test_hit_and_miss_events_increment(self, fresh_stats):
        jitcache._on_event("/jax/compilation_cache/cache_hits")
        jitcache._on_event("/jax/compilation_cache/cache_hits")
        jitcache._on_event("/jax/compilation_cache/cache_misses")
        assert fresh_stats["persistent_cache_hits"] == 2
        assert fresh_stats["persistent_cache_misses"] == 1

    def test_unrelated_events_ignored(self, fresh_stats):
        jitcache._on_event("/jax/some/other/event")
        jitcache._on_event("/jax/compilation_cache/cache_hit")  # not plural
        assert fresh_stats["persistent_cache_hits"] == 0
        assert fresh_stats["persistent_cache_misses"] == 0

    def test_extra_kwargs_tolerated(self, fresh_stats):
        # jax.monitoring passes listener kwargs that vary by version
        jitcache._on_event(
            "/jax/compilation_cache/cache_misses", platform="cpu"
        )
        assert fresh_stats["persistent_cache_misses"] == 1


class TestDurationAccumulation:
    def test_compile_and_trace_durations_accumulate(self, fresh_stats):
        jitcache._on_duration(
            "/jax/core/compile/backend_compile_duration", 1.5
        )
        jitcache._on_duration(
            "/jax/core/compile/backend_compile_duration", 0.25
        )
        jitcache._on_duration("/jax/core/compile/jaxpr_trace_duration", 0.5)
        assert fresh_stats["backend_compile_s"] == pytest.approx(1.75)
        assert fresh_stats["trace_s"] == pytest.approx(0.5)

    def test_unrelated_durations_ignored(self, fresh_stats):
        jitcache._on_duration("/jax/core/lowering_duration", 9.0)
        assert fresh_stats["backend_compile_s"] == 0.0
        assert fresh_stats["trace_s"] == 0.0


class TestCacheStats:
    def test_floats_rounded_ints_passed_through(self, fresh_stats):
        fresh_stats["backend_compile_s"] = 1.23456
        fresh_stats["trace_s"] = 0.005
        fresh_stats["persistent_cache_hits"] = 7
        stats = jitcache.cache_stats()
        assert stats["backend_compile_s"] == 1.23
        assert stats["trace_s"] == 0.01
        assert stats["persistent_cache_hits"] == 7

    def test_snapshot_is_a_copy(self, fresh_stats):
        snapshot = jitcache.cache_stats()
        snapshot["persistent_cache_hits"] = 999
        assert fresh_stats["persistent_cache_hits"] == 0


class TestListenerRegistration:
    def test_register_listeners_is_idempotent(self, monkeypatch):
        import jax.monitoring

        calls = {"event": 0, "duration": 0}
        monkeypatch.setattr(
            jax.monitoring,
            "register_event_listener",
            lambda fn: calls.__setitem__("event", calls["event"] + 1),
        )
        monkeypatch.setattr(
            jax.monitoring,
            "register_event_duration_secs_listener",
            lambda fn: calls.__setitem__("duration", calls["duration"] + 1),
        )
        monkeypatch.setattr(jitcache, "_LISTENERS_ON", False)
        jitcache._register_listeners()
        jitcache._register_listeners()
        jitcache._register_listeners()
        assert calls == {"event": 1, "duration": 1}
