"""Online serving: device-resident registry, micro-batching, REST lane.

Covers the acceptance contract of the serving subsystem
(docs/serving.md): predictions from the registry equal the in-memory
``FittedModel.predict`` bit-for-bit, a rebuild is never served stale,
evictions stay within ``LO_SERVE_BYTES``, a concurrent burst coalesces
into multi-request dispatches, and every failure mode of the REST lane
answers a clean JSON error — never a traceback.
"""

import json
import threading
import time

import numpy as np
import pytest

from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.ml.base import make_classifier
from learningorchestra_tpu.ml.checkpoint import (
    checkpoint_path,
    gather_model,
    write_checkpoint,
)
from learningorchestra_tpu.sched import QueueFullError
from learningorchestra_tpu.serve import (
    MicroBatcher,
    ModelNotFoundError,
    ModelRegistry,
    ServePlane,
)
from learningorchestra_tpu.serve.registry import _model_nbytes
from learningorchestra_tpu.services import model_builder


def body(response):
    return json.loads(response.get_data())


@pytest.fixture()
def data(rng):
    X = rng.normal(size=(200, 6))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


def fit_and_checkpoint(name, X, y, models_dir, kind="lr"):
    X_fit = np.abs(X) if kind == "nb" else X
    model = make_classifier(kind).fit(X_fit, y)
    path = checkpoint_path(str(models_dir), name)
    write_checkpoint(gather_model(model), path)
    return model, path, X_fit


class _FakeModel:
    def predict_both(self, X):
        return (
            np.zeros(len(X), np.int64),
            np.zeros((len(X), 2), np.float32),
        )


class _GateRegistry:
    """Registry stand-in whose get() blocks until the gate opens — the
    deterministic way to hold a forward in flight while the inbox
    fills."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def get(self, path):
        self.calls += 1
        if not self.gate.wait(timeout=10):
            raise TimeoutError("gate never opened")
        return _FakeModel()


def wait_until(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.002)
    return False


class TestRegistry:
    def test_pin_and_hit(self, data, tmp_path):
        X, y = data
        _, path, _ = fit_and_checkpoint("r_prediction_lr", X, y, tmp_path)
        registry = ModelRegistry(capacity=10**9)
        first = registry.get(path)
        second = registry.get(path)
        assert first is second  # pinned, not reloaded
        stats = registry.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        assert stats["models"] == 1 and stats["bytes"] > 0

    def test_missing_artifact_raises(self, tmp_path):
        registry = ModelRegistry(capacity=10**9)
        with pytest.raises(ModelNotFoundError):
            registry.get(str(tmp_path / "never_built.model"))

    def test_deleted_artifact_drops_entry(self, data, tmp_path):
        X, y = data
        _, path, _ = fit_and_checkpoint("d_prediction_lr", X, y, tmp_path)
        registry = ModelRegistry(capacity=10**9)
        registry.get(path)
        import os

        os.remove(path)
        with pytest.raises(ModelNotFoundError):
            registry.get(path)
        stats = registry.stats()
        assert stats["models"] == 0 and stats["bytes"] == 0

    def test_deleted_mid_load_maps_to_not_found(self, data, tmp_path, monkeypatch):
        """An artifact that vanishes between the rev stat and the open
        is the same late-404 as a failed stat — never a 500."""
        X, y = data
        _, path, _ = fit_and_checkpoint("mid_prediction_lr", X, y, tmp_path)
        registry = ModelRegistry(capacity=10**9)

        def vanished(self, p):
            raise FileNotFoundError(p)

        monkeypatch.setattr(ModelRegistry, "_load", vanished)
        with pytest.raises(ModelNotFoundError):
            registry.get(path)

    def test_lru_eviction_stays_within_budget(self, data, tmp_path):
        X, y = data
        paths = []
        for index in range(3):
            _, path, _ = fit_and_checkpoint(
                f"e{index}_prediction_lr", X, y, tmp_path
            )
            paths.append(path)
        probe = ModelRegistry(capacity=10**9)
        sizes = [_model_nbytes(probe.get(path)) for path in paths]
        # room for exactly two models: loading the third evicts the LRU
        capacity = sizes[0] + sizes[1]
        registry = ModelRegistry(capacity=capacity)
        for path in paths:
            registry.get(path)
            assert registry.stats()["bytes"] <= capacity
        stats = registry.stats()
        assert stats["evictions"] >= 1 and stats["models"] == 2
        # the evicted (least recently used) model misses again
        misses_before = registry.stats()["misses"]
        registry.get(paths[0])
        assert registry.stats()["misses"] == misses_before + 1

    def test_zero_budget_host_fallback(self, data, tmp_path):
        X, y = data
        model, path, X_fit = fit_and_checkpoint(
            "hf_prediction_lr", X, y, tmp_path
        )
        registry = ModelRegistry(capacity=0)
        first = registry.get(path)
        second = registry.get(path)
        assert first is not second  # nothing pinned
        stats = registry.stats()
        assert stats["bytes"] == 0 and stats["models"] == 0
        assert stats["misses"] == 2 and stats["hits"] == 0
        np.testing.assert_array_equal(
            first.predict(X_fit.astype(np.float32)),
            model.predict(X_fit.astype(np.float32)),
        )


class TestCheckpointRoundTrip:
    """write_checkpoint → registry load → predict equals the in-memory
    FittedModel.predict bit-for-bit, per model kind — including after a
    simulated rebuild bumps the artifact (never stale HBM)."""

    @pytest.mark.parametrize("kind", ["lr", "nb", "dt", "rf", "gb"])
    def test_registry_matches_in_memory_model(self, kind, data, tmp_path):
        X, y = data
        model, path, X_fit = fit_and_checkpoint(
            f"rt_{kind}_prediction", X, y, tmp_path, kind=kind
        )
        registry = ModelRegistry(capacity=10**9)
        served = registry.get(path)
        rows = X_fit.astype(np.float32)
        expect_labels, expect_probs = model.predict_both(rows)
        got_labels, got_probs = served.predict_both(rows)
        np.testing.assert_array_equal(got_labels, expect_labels)
        np.testing.assert_array_equal(got_probs, expect_probs)

        # simulated rebuild: flipped labels overwrite the SAME artifact
        rebuilt = make_classifier(kind).fit(X_fit, 1 - y)
        write_checkpoint(gather_model(rebuilt), path)
        served = registry.get(path)
        flip_labels, flip_probs = rebuilt.predict_both(rows)
        np.testing.assert_array_equal(served.predict_both(rows)[0], flip_labels)
        np.testing.assert_array_equal(served.predict_both(rows)[1], flip_probs)
        assert registry.stats()["invalidations"] == 1


class TestMicroBatcher:
    def test_burst_coalesces_into_batched_dispatches(self, data, tmp_path):
        """The acceptance burst: >= 64 concurrent single-row requests
        serve in far fewer dispatches (mean batch size > 1), every
        answer equal to the in-memory model's."""
        X, y = data
        model, path, _ = fit_and_checkpoint(
            "b_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(
            capacity=10**9, window_s=0.005, max_batch=32, inbox_cap=256
        )
        try:
            rows = X.astype(np.float32)
            requests = [None] * 64
            barrier = threading.Barrier(64)

            def submit(index):
                barrier.wait()
                requests[index] = plane.submit(path, rows[index : index + 1])

            threads = [
                threading.Thread(target=submit, args=(i,)) for i in range(64)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
            for request in requests:
                assert request.wait(30) and request.error is None
            expected = model.predict(rows[:64])
            got = np.array([requests[i].labels[0] for i in range(64)])
            np.testing.assert_array_equal(got, expected)
            stats = plane.batcher.stats()
            assert stats["batched_requests"] == 64
            assert stats["batches"] < 64  # >= 1 multi-request dispatch
            assert stats["mean_batch_size"] > 1
        finally:
            plane.close()

    def test_width_mismatch_fails_alone(self, data, tmp_path):
        X, y = data
        model, path, _ = fit_and_checkpoint(
            "w_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(
            capacity=10**9, window_s=0.05, max_batch=8, inbox_cap=32
        )
        try:
            good = plane.submit(path, X[:2].astype(np.float32))
            bad = plane.submit(path, np.zeros((1, 2), np.float32))
            assert good.wait(30) and bad.wait(30)
            assert good.error is None
            np.testing.assert_array_equal(
                good.labels, model.predict(X[:2].astype(np.float32))
            )
            assert bad.error is not None  # wrong width fails only itself
        finally:
            plane.close()

    def test_bounded_inbox_rejects_with_retry_after(self):
        registry = _GateRegistry()
        batcher = MicroBatcher(
            registry, window_s=0.0, max_batch=4, inbox_cap=1
        )
        try:
            first = batcher.submit("m", np.zeros((1, 3), np.float32))
            # worker picked first up and is now blocked in the forward
            assert wait_until(lambda: registry.calls == 1)
            second = batcher.submit("m", np.zeros((1, 3), np.float32))
            with pytest.raises(QueueFullError) as excinfo:
                batcher.submit("m", np.zeros((1, 3), np.float32))
            assert excinfo.value.job_class == "serve"
            assert excinfo.value.retry_after_s >= 1
            assert batcher.stats()["rejected"] == 1
            registry.gate.set()
            assert first.wait(10) and second.wait(10)
            assert first.error is None and second.error is None
        finally:
            registry.gate.set()
            batcher.close()

    def test_window_zero_drains_backlog_into_one_batch(self):
        registry = _GateRegistry()
        batcher = MicroBatcher(
            registry, window_s=0.0, max_batch=16, inbox_cap=32
        )
        try:
            first = batcher.submit("m", np.zeros((1, 3), np.float32))
            assert wait_until(lambda: registry.calls == 1)
            backlog = [
                batcher.submit("m", np.zeros((1, 3), np.float32))
                for _ in range(5)
            ]
            registry.gate.set()
            for request in [first] + backlog:
                assert request.wait(10) and request.error is None
            # the 5 queued while the first forward ran became ONE batch
            assert batcher.batches == 2
        finally:
            registry.gate.set()
            batcher.close()

    def test_submit_rejects_malformed_rows_and_lane_survives(
        self, data, tmp_path
    ):
        """Malformed rows fail on the CALLER's thread (ValueError), so
        a bad library submission can never kill the worker loop and
        wedge the lane for every later request."""
        X, y = data
        _, path, _ = fit_and_checkpoint("mv_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            with pytest.raises(ValueError):
                plane.submit(path, np.zeros(3, np.float32))  # 1-D
            with pytest.raises(ValueError):
                plane.submit(path, np.zeros((0, 3), np.float32))  # empty
            good = plane.submit(path, X[:1].astype(np.float32))
            assert good.wait(30) and good.error is None
        finally:
            plane.close()

    def test_abandoned_requests_never_dispatch(self):
        """A timed-out (503) client's request is dropped at dispatch —
        the registry is never consulted and no forward runs for it."""
        registry = _GateRegistry()
        batcher = MicroBatcher(
            registry, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            first = batcher.submit("m", np.zeros((1, 3), np.float32))
            assert wait_until(lambda: registry.calls == 1)
            dead = batcher.submit("m", np.zeros((1, 3), np.float32))
            dead.abandon()  # what the route does after answering 503
            registry.gate.set()
            live = batcher.submit("m", np.zeros((1, 3), np.float32))
            assert first.wait(10) and dead.wait(10) and live.wait(10)
            assert first.error is None and live.error is None
            assert dead.labels is None and dead.error is not None
            # batches: [first], [live] — the abandoned one cost nothing
            assert registry.calls == 2
        finally:
            registry.gate.set()
            batcher.close()

    def test_multi_row_requests_bound_collection(self):
        """Accumulated rows >= max_batch stop the collection early, so
        one dispatch never drains an unbounded row count."""
        registry = _GateRegistry()
        batcher = MicroBatcher(
            registry, window_s=0.05, max_batch=4, inbox_cap=16
        )
        try:
            first = batcher.submit("m", np.zeros((1, 3), np.float32))
            assert wait_until(lambda: registry.calls == 1)
            # 4 rows reach the row budget exactly; the fifth request
            # must land in a SEPARATE dispatch
            wide = batcher.submit("m", np.zeros((4, 3), np.float32))
            tail = batcher.submit("m", np.zeros((1, 3), np.float32))
            registry.gate.set()
            for request in (first, wide, tail):
                assert request.wait(10) and request.error is None
            assert batcher.batches == 3
        finally:
            registry.gate.set()
            batcher.close()

    def test_close_fails_pending(self):
        registry = _GateRegistry()
        batcher = MicroBatcher(
            registry, window_s=0.0, max_batch=4, inbox_cap=8
        )
        first = batcher.submit("m", np.zeros((1, 3), np.float32))
        assert wait_until(lambda: registry.calls == 1)
        stuck = batcher.submit("m", np.zeros((1, 3), np.float32))
        registry.gate.set()
        batcher.close()
        assert first.wait(10)
        assert stuck.wait(10)  # answered: completed or failed, never hung
        with pytest.raises(RuntimeError):
            batcher.submit("m", np.zeros((1, 3), np.float32))


class TestServeRoutes:
    def make_app(self, models_dir, plane):
        return model_builder.create_app(
            InMemoryStore(), models_dir=str(models_dir), serve=plane
        )

    def test_predict_matches_in_memory_model(self, data, tmp_path):
        X, y = data
        model, _, _ = fit_and_checkpoint(
            "svc_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=8, inbox_cap=32
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            rows = X[:5].astype(np.float32)
            response = client.post(
                "/models/svc_prediction_lr/predict",
                json={"rows": rows.tolist()},
            )
            assert response.status_code == 200
            result = body(response)["result"]
            assert result["model"] == "svc_prediction_lr"
            np.testing.assert_array_equal(
                np.array(result["predictions"]), model.predict(rows)
            )
            probs = np.array(result["probabilities"], np.float32)
            np.testing.assert_array_equal(probs, model.predict_proba(rows))
            # a single flat row is one request
            response = client.post(
                "/models/svc_prediction_lr/predict",
                json={"rows": rows[0].tolist()},
            )
            assert response.status_code == 200
            assert len(body(response)["result"]["predictions"]) == 1
        finally:
            plane.close()

    def test_unknown_model_404_json(self, tmp_path):
        plane = ServePlane(capacity=0, window_s=0.0, max_batch=2, inbox_cap=4)
        try:
            client = self.make_app(tmp_path, plane).test_client()
            response = client.post(
                "/models/never_built/predict", json={"rows": [[1.0, 2.0]]}
            )
            assert response.status_code == 404
            assert body(response) == {"result": "file_not_found"}
            # traversal-looking names are rejected the same clean way
            response = client.post(
                "/models/..%2Fetc/predict", json={"rows": [[1.0]]}
            )
            assert response.status_code == 404
        finally:
            plane.close()

    def test_malformed_rows_406_json(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("mf_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            url = "/models/mf_prediction_lr/predict"
            assert client.post(url, json={"nope": 1}).status_code == 406
            assert client.post(url, json={"rows": []}).status_code == 406
            ragged = client.post(url, json={"rows": [[1, 2], [3]]})
            assert ragged.status_code == 406
            assert body(ragged) == {"result": "invalid_rows"}
            strings = client.post(url, json={"rows": [["a", "b"]]})
            assert strings.status_code == 406
            # JSON null converts to NaN without raising — must still 406,
            # never 200 with NaN "probabilities"
            nulls = client.post(
                url, json={"rows": [[1.0, None, 2.0, 3.0, 4.0, 5.0]]}
            )
            assert nulls.status_code == 406
            assert body(nulls) == {"result": "invalid_rows"}
        finally:
            plane.close()

    def test_forward_failure_is_clean_json_500(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("ff_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            # wrong feature width survives np.asarray but fails the
            # forward — the route must answer JSON, not a traceback
            response = client.post(
                "/models/ff_prediction_lr/predict",
                json={"rows": [[1.0, 2.0]]},
            )
            assert response.status_code == 500
            message = body(response)["result"]
            assert message.startswith("prediction_failed:")
            assert "Traceback" not in message
        finally:
            plane.close()

    def test_oversized_request_413(self, data, tmp_path, monkeypatch):
        X, y = data
        fit_and_checkpoint("big_prediction_lr", X, y, tmp_path)
        monkeypatch.setenv("LO_SERVE_MAX_ROWS", "8")
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            url = "/models/big_prediction_lr/predict"
            over = client.post(url, json={"rows": X[:9].tolist()})
            assert over.status_code == 413
            assert body(over) == {"result": "too_many_rows"}
            at_cap = client.post(url, json={"rows": X[:8].tolist()})
            assert at_cap.status_code == 200
        finally:
            plane.close()

    def test_inbox_full_429_with_retry_after(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("full_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=2, inbox_cap=1
        )
        gate = _GateRegistry()
        plane.batcher.registry = gate  # hold the forward in flight
        try:
            client = self.make_app(tmp_path, plane).test_client()
            url = "/models/full_prediction_lr/predict"
            payload = {"rows": X[:1].tolist()}
            results = []

            def blocked():
                results.append(client.post(url, json=payload).status_code)

            runner = threading.Thread(target=blocked)
            runner.start()
            assert wait_until(lambda: gate.calls == 1)
            filler = threading.Thread(target=blocked)
            filler.start()
            assert wait_until(lambda: plane.batcher.depth() == 1)
            rejected = client.post(url, json=payload)
            assert rejected.status_code == 429
            assert body(rejected)["result"] == "queue_full"
            assert body(rejected)["job_class"] == "serve"
            assert int(rejected.headers["Retry-After"]) >= 1
            gate.gate.set()
            runner.join(10)
            filler.join(10)
        finally:
            gate.gate.set()
            plane.close()

    def test_slow_forward_times_out_503(self, data, tmp_path, monkeypatch):
        X, y = data
        fit_and_checkpoint("slow_prediction_lr", X, y, tmp_path)
        monkeypatch.setenv("LO_SERVE_TIMEOUT_S", "0.05")
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=2, inbox_cap=4
        )
        gate = _GateRegistry()
        plane.batcher.registry = gate
        try:
            client = self.make_app(tmp_path, plane).test_client()
            response = client.post(
                "/models/slow_prediction_lr/predict",
                json={"rows": X[:1].tolist()},
            )
            assert response.status_code == 503
            assert body(response) == {"result": "predict_timeout"}
        finally:
            gate.gate.set()
            plane.close()

    def test_rebuild_served_fresh_through_route(self, data, tmp_path):
        X, y = data
        _, path, X_fit = fit_and_checkpoint(
            "rb_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=8, inbox_cap=16
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            url = "/models/rb_prediction_lr/predict"
            rows = X_fit[:8].astype(np.float32)
            first = body(client.post(url, json={"rows": rows.tolist()}))
            rebuilt = make_classifier("lr").fit(X_fit, 1 - y)
            write_checkpoint(gather_model(rebuilt), path)
            second = body(client.post(url, json={"rows": rows.tolist()}))
            np.testing.assert_array_equal(
                np.array(second["result"]["predictions"]),
                rebuilt.predict(rows),
            )
            # flipped labels: the rebuild is visibly NOT the old model
            assert second["result"]["predictions"] != first["result"][
                "predictions"
            ]
            assert plane.registry.stats()["invalidations"] == 1
        finally:
            plane.close()

    def test_registry_disabled_still_serves_correctly(self, data, tmp_path):
        """LO_SERVE_BYTES=0 (capacity 0): host-memory fallback path —
        nothing pinned, predictions still exact."""
        X, y = data
        model, _, _ = fit_and_checkpoint(
            "nofb_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(capacity=0, window_s=0.0, max_batch=4, inbox_cap=8)
        try:
            client = self.make_app(tmp_path, plane).test_client()
            rows = X[:4].astype(np.float32)
            response = client.post(
                "/models/nofb_prediction_lr/predict",
                json={"rows": rows.tolist()},
            )
            assert response.status_code == 200
            np.testing.assert_array_equal(
                np.array(body(response)["result"]["predictions"]),
                model.predict(rows),
            )
            stats = plane.registry.stats()
            assert stats["bytes"] == 0 and stats["models"] == 0
        finally:
            plane.close()

    def test_listing_and_status_carry_serving_info(self, data, tmp_path):
        X, y = data
        fit_and_checkpoint("ls_prediction_lr", X, y, tmp_path)
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=4, inbox_cap=8
        )
        try:
            client = self.make_app(tmp_path, plane).test_client()
            listing = body(client.get("/models"))
            assert listing["result"] == ["ls_prediction_lr"]
            assert listing["serving"]["registry"]["models"] == 0
            info = body(client.get("/models/ls_prediction_lr"))["result"]
            assert info["serving"] == {"resident": False}
            client.post(
                "/models/ls_prediction_lr/predict",
                json={"rows": X[:1].tolist()},
            )
            info = body(client.get("/models/ls_prediction_lr"))["result"]
            assert info["serving"]["resident"] is True
            assert info["serving"]["bytes"] > 0
        finally:
            plane.close()


class TestLoadGenerator:
    def _serve_app(self, data, tmp_path, **knobs):
        X, y = data
        model, _, _ = fit_and_checkpoint(
            "lg_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(capacity=10**9, **knobs)
        app = model_builder.create_app(
            InMemoryStore(), models_dir=str(tmp_path), serve=plane
        )
        return X, plane, app

    def _run(self, X, plane, app, clients, requests_per_client):
        from learningorchestra_tpu.serve.loadgen import run_closed_loop

        handles = [app.test_client() for _ in range(clients)]
        row = X[:1].tolist()

        def send(index):
            response = handles[index].post(
                "/models/lg_prediction_lr/predict", json={"rows": row}
            )
            assert response.status_code == 200

        return run_closed_loop(send, clients, requests_per_client)

    def test_smoke_closed_loop(self, data, tmp_path):
        """Tier-1 smoke config: small client counts, few requests."""
        X, plane, app = self._serve_app(
            data, tmp_path, window_s=0.001, max_batch=16, inbox_cap=256
        )
        try:
            for clients in (1, 8):
                stats = self._run(X, plane, app, clients, 10)
                assert stats["requests"] == clients * 10
                assert stats["p99_ms"] >= stats["p50_ms"] > 0
                assert stats["predictions_per_s"] > 0
            assert plane.batcher.stats()["batched_requests"] == 90
        finally:
            plane.close()

    @pytest.mark.slow
    def test_concurrency_sweep_batches(self, data, tmp_path):
        """The bench section's shape at full size: 64 concurrent
        closed-loop clients must achieve mean batch size > 1."""
        X, plane, app = self._serve_app(
            data, tmp_path, window_s=0.001, max_batch=64, inbox_cap=1024
        )
        try:
            before = plane.batcher.stats()
            stats = self._run(X, plane, app, 64, 50)
            after = plane.batcher.stats()
            batches = after["batches"] - before["batches"]
            grouped = after["batched_requests"] - before["batched_requests"]
            assert stats["requests"] == 64 * 50
            assert grouped / batches > 1  # micro-batching engaged
        finally:
            plane.close()

    def test_sessions_close_on_success_and_send_error(self):
        """Per-client sessions are handed to send and closed in
        ``finally`` — including when a send raises mid-loop (the leak
        path: a failed client used to abandon its connection)."""
        from learningorchestra_tpu.serve.loadgen import run_closed_loop

        class Session:
            def __init__(self, index):
                self.index = index
                self.closed = False

            def close(self):
                self.closed = True

        sessions = []

        def session_factory(index):
            session = Session(index)
            sessions.append(session)
            return session

        def send(index, session):
            assert session.index == index
            if index == 2:
                raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            run_closed_loop(
                send, 4, 3, session_factory=session_factory
            )
        assert len(sessions) == 4
        assert all(session.closed for session in sessions)

        sessions.clear()
        stats = run_closed_loop(
            lambda index, session: None,
            3,
            2,
            session_factory=session_factory,
        )
        assert stats["requests"] == 6
        assert all(session.closed for session in sessions)

    def test_session_factory_failure_aborts_barrier(self):
        """A client dying BEFORE the start barrier must abort it (no
        deadlock) and surface the root cause, not the collateral
        BrokenBarrierError the other clients see."""
        from learningorchestra_tpu.serve.loadgen import run_closed_loop

        opened = []

        class Session:
            def __init__(self):
                self.closed = False
                opened.append(self)

            def close(self):
                self.closed = True

        def session_factory(index):
            if index == 1:
                raise OSError("connect refused")
            return Session()

        with pytest.raises(OSError, match="connect refused"):
            run_closed_loop(
                lambda index, session: None,
                3,
                5,
                session_factory=session_factory,
            )
        assert all(session.closed for session in opened)

    def test_http_sender_parameterizes_targets(self):
        """Client i's session targets targets[i % len(targets)] — one
        target is router mode, several spread clients across replicas.
        No hardcoded single target anywhere."""
        from learningorchestra_tpu.serve.loadgen import (
            http_predict_sender,
        )

        targets = ["127.0.0.1:5102", "http://127.0.0.1:5103"]
        send, session_factory = http_predict_sender(
            targets, "m", [[1.0]]
        )
        assigned = [session_factory(i).target for i in range(4)]
        assert assigned == [
            "127.0.0.1:5102",
            "http://127.0.0.1:5103",
            "127.0.0.1:5102",
            "http://127.0.0.1:5103",
        ]
        with pytest.raises(ValueError, match="at least one target"):
            http_predict_sender([], "m", [[1.0]])


class TestServeConfig:
    def test_defaults(self, monkeypatch):
        from learningorchestra_tpu.serve import config

        for knob in (
            "LO_SERVE_BYTES",
            "LO_SERVE_BATCH_WINDOW_MS",
            "LO_SERVE_MAX_BATCH",
            "LO_SERVE_MAX_ROWS",
            "LO_SERVE_QUEUE_CAP",
            "LO_SERVE_TIMEOUT_S",
        ):
            monkeypatch.delenv(knob, raising=False)
        resolved = config.validate_all()
        assert resolved["serve_bytes"] == 1_000_000_000
        assert resolved["batch_window_s"] == pytest.approx(0.001)
        assert resolved["max_batch"] == 64
        assert resolved["max_rows"] == 4096
        assert resolved["queue_cap"] == 256
        assert resolved["request_timeout_s"] == 30.0

    @pytest.mark.parametrize(
        "knob,value",
        [
            ("LO_SERVE_BYTES", "lots"),
            ("LO_SERVE_BYTES", "-1"),
            ("LO_SERVE_BATCH_WINDOW_MS", "-0.5"),
            ("LO_SERVE_BATCH_WINDOW_MS", "soon"),
            ("LO_SERVE_MAX_BATCH", "0"),
            ("LO_SERVE_MAX_BATCH", "1.5"),  # count knobs never truncate
            ("LO_SERVE_MAX_ROWS", "0"),
            ("LO_SERVE_MAX_ROWS", "2.5"),
            ("LO_SERVE_QUEUE_CAP", "0"),
            ("LO_SERVE_QUEUE_CAP", "ten"),
            ("LO_SERVE_TIMEOUT_S", "0"),
        ],
    )
    def test_rejects_bad_values(self, monkeypatch, knob, value):
        from learningorchestra_tpu.serve import config

        monkeypatch.setenv(knob, value)
        with pytest.raises(ValueError):
            config.validate_all()

    def test_zero_window_and_zero_bytes_are_valid(self, monkeypatch):
        from learningorchestra_tpu.serve import config

        monkeypatch.setenv("LO_SERVE_BYTES", "0")
        monkeypatch.setenv("LO_SERVE_BATCH_WINDOW_MS", "0")
        resolved = config.validate_all()
        assert resolved["serve_bytes"] == 0
        assert resolved["batch_window_s"] == 0.0


class TestClientSdk:
    def test_predict_and_list_models_over_http(self, data, tmp_path):
        """The SDK lane: Model.predict / Model.list_models against a
        live server — no raw HTTP in user scripts (docs/serving.md)."""
        import learningorchestra_tpu.client as lo_client
        from learningorchestra_tpu.utils.web import ServerThread

        X, y = data
        model, _, _ = fit_and_checkpoint(
            "sdk_prediction_lr", X, y, tmp_path
        )
        plane = ServePlane(
            capacity=10**9, window_s=0.0, max_batch=8, inbox_cap=32
        )
        app = model_builder.create_app(
            InMemoryStore(), models_dir=str(tmp_path), serve=plane
        )
        server = ServerThread(app, "127.0.0.1", 0).start()
        saved_port = lo_client.Model.MODEL_BUILDER_PORT
        try:
            lo_client.Model.MODEL_BUILDER_PORT = str(server.port)
            lo_client.Context("127.0.0.1")
            sdk = lo_client.Model()
            listing = sdk.list_models(pretty_response=False)
            assert listing["result"] == ["sdk_prediction_lr"]
            rows = X[:3].astype(np.float32)
            result = sdk.predict(
                "sdk_prediction_lr", rows.tolist(), pretty_response=False
            )
            np.testing.assert_array_equal(
                np.array(result["result"]["predictions"]),
                model.predict(rows),
            )
            # the reference-parity PyPI shim exposes the same surface
            from learning_orchestra_client import Model as ShimModel

            assert ShimModel is lo_client.Model
            with pytest.raises(Exception, match="file_not_found"):
                sdk.predict("ghost", [[1.0]], pretty_response=False)
        finally:
            lo_client.Model.MODEL_BUILDER_PORT = saved_port
            server.stop()
            plane.close()
