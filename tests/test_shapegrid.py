"""The shared padded-shape grid (utils/shapegrid.py).

One copy of the quarter-octave math serves the sharding row pad, the
serving MicroBatcher's fixed dispatch shape, and the coalescer's job
axis — these tests pin the grid's contract so none of the three can
drift.
"""

import numpy as np
import pytest

from learningorchestra_tpu.utils.shapegrid import (
    bucket_count,
    grid_size,
    pad_axis0,
    padded_indices,
)


class TestBucketCount:
    def test_small_counts_pass_through(self):
        for n in range(1, 9):
            assert bucket_count(n) == n

    def test_powers_of_two_pass_through(self):
        for k in range(3, 20):
            assert bucket_count(1 << k) == 1 << k

    def test_grid_values_are_quarter_octave(self):
        # every bucket is {4,5,6,7} x 2^k for some k
        for n in list(range(9, 4097)) + [10**6, 10**7 + 3]:
            bucket = bucket_count(n)
            assert bucket >= n
            mantissa = bucket
            while mantissa % 2 == 0:
                mantissa //= 2
            assert mantissa in (1, 3, 5, 7), (n, bucket)

    def test_monotone_and_bounded_waste(self):
        previous = 0
        for n in range(1, 3000):
            bucket = bucket_count(n)
            assert bucket >= previous
            previous = bucket
            if n > 8:
                assert bucket <= n * 1.25  # worst-case padding waste

    def test_idempotent(self):
        for n in range(1, 3000):
            assert bucket_count(bucket_count(n)) == bucket_count(n)

    def test_sharding_delegates_here(self):
        # the data-plane row pad is THIS grid, not a private copy
        from learningorchestra_tpu.parallel.sharding import bucket_rows

        for n in (1, 7, 9, 100, 1000, 12345):
            assert bucket_rows(n) == bucket_count(n)


class TestGridSize:
    def test_floor_pins_small_counts(self):
        # the MicroBatcher contract: all small traffic shares ONE shape
        for n in range(1, 65):
            assert grid_size(n, floor=64) == 64

    def test_above_floor_rides_the_grid(self):
        assert grid_size(65, floor=64) == bucket_count(65)
        assert grid_size(1000, floor=64) == bucket_count(1000)

    def test_no_floor_is_plain_bucketing(self):
        for n in (1, 5, 9, 100):
            assert grid_size(n) == bucket_count(n)

    def test_shape_buckets_knob_disables_above_floor_only(self, monkeypatch):
        # LO_SHAPE_BUCKETS=0 (read once at import; patch the flag):
        # above-floor counts get minimal padding, the fixed floor stays
        from learningorchestra_tpu.utils import shapegrid

        monkeypatch.setattr(shapegrid, "_BUCKETS_ENABLED", False)
        assert shapegrid.grid_size(65, floor=64) == 65
        assert shapegrid.grid_size(1000, floor=64) == 1000
        assert shapegrid.grid_size(50, floor=64) == 64


class TestPadHelpers:
    def test_pad_axis0_zero_fills(self):
        array = np.arange(6, dtype=np.float32).reshape(3, 2)
        padded = pad_axis0(array, 5)
        assert padded.shape == (5, 2)
        np.testing.assert_array_equal(padded[:3], array)
        assert not padded[3:].any()

    def test_pad_axis0_noop_at_target(self):
        array = np.ones((4, 2), np.float32)
        assert pad_axis0(array, 4) is array
        assert pad_axis0(array, 2) is array

    def test_padded_indices_replicate_slot_zero(self):
        assert padded_indices(3, 5) == [0, 1, 2, 0, 0]
        assert padded_indices(4, 4) == [0, 1, 2, 3]

    def test_padded_indices_need_a_real_entry(self):
        with pytest.raises(ValueError):
            padded_indices(0, 4)
