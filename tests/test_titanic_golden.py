"""Titanic golden-parity test: the reference's documented walkthrough,
end to end over the REST surface.

The reference's de-facto integration test is the Titanic usage example
(reference: learning_orchestra_client/readme.md "usage example"):
ingest train+test CSVs, project the documented field subset, convert
types, then ``create_model`` with the VERBATIM published
``preprocessing_code`` and all five classifiers. Expected outputs are
documented in reference docs/database_api.md:76-83 (the
``titanic_testing_new_prediction_nb`` metadata: NB F1 0.7031 /
accuracy 0.7035).

Data: this environment has no network egress, so tests/data/ carries a
REGENERATED Titanic (tests/data/make_titanic.py) matched to the real
dataset's published joint statistics — exact (Sex, Pclass) survival
crosstab, title/age/family/fare/embarkation distributions, 177 missing
ages, 891+418 rows.

What is asserted, and why not ±0.05 of the published NB number: the
documented preprocessor assembles ``training_df.columns[:]`` — which
includes ``label`` AND ``PassengerId`` — so lr/dt/rf/gb separate the
eval split (near-)perfectly off the leaked label, while multinomial NB
is dominated by the PassengerId pseudo-counts (values up to 891 swamp
every other feature's mass), making its exact score a function of the
ORIGINAL file's id/survival interleaving — unreproducible from summary
statistics (measured spread across faithful regenerations: 0.86-0.94
vs the published 0.7035). The STABLE invariants of the documented run
are asserted instead:

- the verbatim preprocessor executes through the pyspark facade;
- leak classifiers (lr/dt/rf/gb) reach >= 0.95 accuracy;
- NB is the weakest classifier by a margin (the published run's
  signature: 0.70 vs 1.0);
- prediction collections have the documented metadata shape
  (F1/accuracy as STRINGS, fit_time, classificator).

A second test runs the same pipeline with the leak removed (label +
PassengerId dropped from the assembler) — the configuration whose
quality IS reproducible from distributions — and pins all five
classifiers to the canonical Titanic accuracy band.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.services import (
    data_type_handler,
    database_api,
    model_builder,
    projection,
)

DATA = os.path.join(os.path.dirname(__file__), "data")
TRAIN_CSV = os.path.join(DATA, "titanic_train.csv")
TEST_CSV = os.path.join(DATA, "titanic_test.csv")

# The verbatim preprocessing_code from the reference walkthrough
# (learning_orchestra_client/readme.md), reproduced as published.
PREPROCESSING_CODE = r'''
from pyspark.ml import Pipeline
from pyspark.sql.functions import (
    mean, col, split,
    regexp_extract, when, lit)

from pyspark.ml.feature import (
    VectorAssembler,
    StringIndexer
)

TRAINING_DF_INDEX = 0
TESTING_DF_INDEX = 1

training_df = training_df.withColumnRenamed('Survived', 'label')
testing_df = testing_df.withColumn('label', lit(0))
datasets_list = [training_df, testing_df]

for index, dataset in enumerate(datasets_list):
    dataset = dataset.withColumn(
        "Initial",
        regexp_extract(col("Name"), "([A-Za-z]+)\.", 1))
    datasets_list[index] = dataset

misspelled_initials = [
    'Mlle', 'Mme', 'Ms', 'Dr',
    'Major', 'Lady', 'Countess',
    'Jonkheer', 'Col', 'Rev',
    'Capt', 'Sir', 'Don'
]
correct_initials = [
    'Miss', 'Miss', 'Miss', 'Mr',
    'Mr', 'Mrs', 'Mrs',
    'Other', 'Other', 'Other',
    'Mr', 'Mr', 'Mr'
]
for index, dataset in enumerate(datasets_list):
    dataset = dataset.replace(misspelled_initials, correct_initials)
    datasets_list[index] = dataset


initials_age = {"Miss": 22,
                "Other": 46,
                "Master": 5,
                "Mr": 33,
                "Mrs": 36}
for index, dataset in enumerate(datasets_list):
    for initial, initial_age in initials_age.items():
        dataset = dataset.withColumn(
            "Age",
            when((dataset["Initial"] == initial) &
                 (dataset["Age"].isNull()), initial_age).otherwise(
                    dataset["Age"]))
        datasets_list[index] = dataset


for index, dataset in enumerate(datasets_list):
    dataset = dataset.na.fill({"Embarked": 'S'})
    datasets_list[index] = dataset


for index, dataset in enumerate(datasets_list):
    dataset = dataset.withColumn("Family_Size", col('SibSp')+col('Parch'))
    dataset = dataset.withColumn('Alone', lit(0))
    dataset = dataset.withColumn(
        "Alone",
        when(dataset["Family_Size"] == 0, 1).otherwise(dataset["Alone"]))
    datasets_list[index] = dataset


text_fields = ["Sex", "Embarked", "Initial"]
for column in text_fields:
    for index, dataset in enumerate(datasets_list):
        dataset = StringIndexer(
            inputCol=column, outputCol=column+"_index").\
                fit(dataset).\
                transform(dataset)
        datasets_list[index] = dataset


non_required_columns = ["Name", "Embarked", "Sex", "Initial"]
for index, dataset in enumerate(datasets_list):
    dataset = dataset.drop(*non_required_columns)
    datasets_list[index] = dataset


training_df = datasets_list[TRAINING_DF_INDEX]
testing_df = datasets_list[TESTING_DF_INDEX]

assembler = VectorAssembler(
    inputCols=training_df.columns[:],
    outputCol="features")
assembler.setHandleInvalid('skip')

features_training = assembler.transform(training_df)
(features_training, features_evaluation) =\
    features_training.randomSplit([0.8, 0.2], seed=33)
features_testing = assembler.transform(testing_df)
'''

# Leak-free variant: identical pipeline, but the assembler excludes the
# leaked label and the id column — the configuration whose model quality
# is reproducible from the data's distributions.
CLEAN_ASSEMBLER = """
assembler = VectorAssembler(
    inputCols=[c for c in training_df.columns
               if c not in ("label", "PassengerId")],
    outputCol="features")
"""
CLEAN_PREPROCESSING_CODE = PREPROCESSING_CODE.replace(
    """
assembler = VectorAssembler(
    inputCols=training_df.columns[:],
    outputCol="features")
""",
    CLEAN_ASSEMBLER,
)
assert CLEAN_PREPROCESSING_CODE != PREPROCESSING_CODE

# The documented projection field set (reference docs/database_api.md
# "Preprocessed files metadata").
PROJECTION_FIELDS = [
    "PassengerId", "Survived", "Pclass", "Name", "Sex", "Age",
    "SibSp", "Parch", "Embarked",
]


def _drive_walkthrough(preprocessor_code: str, classifiers: list) -> dict:
    """The reference walkthrough over the REST surface (service test
    clients — same WSGI apps the deployed services run). Returns
    {classifier: prediction-metadata-document}."""
    store = InMemoryStore()
    db = database_api.create_app(store, jobs=JobManager()).test_client()
    proj = projection.create_app(store).test_client()
    dtype = data_type_handler.create_app(store).test_client()
    models = model_builder.create_app(store).test_client()

    for name, path in (
        ("titanic_training", TRAIN_CSV),
        ("titanic_testing", TEST_CSV),
    ):
        response = db.post("/files", json={"filename": name, "url": path})
        assert response.status_code == 201, response.get_data()
        # ingest is async (201-then-poll): poll the finished flag with a
        # real wall-clock bound (~15 s)
        for _ in range(300):
            meta = json.loads(
                db.get(f"/files/{name}?skip=0&limit=1&query={{}}").get_data()
            )["result"][0]
            if meta.get("finished"):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(f"ingest of {name} never finished")

    for parent, out in (
        ("titanic_training", "titanic_training_projection"),
        ("titanic_testing", "titanic_testing_projection"),
    ):
        fields = (
            PROJECTION_FIELDS
            if parent == "titanic_training"
            else [f for f in PROJECTION_FIELDS if f != "Survived"]
        )
        response = proj.post(
            f"/projections/{parent}",
            json={"projection_filename": out, "fields": fields},
        )
        assert response.status_code == 201, response.get_data()

    types = {
        "Age": "number",
        "Parch": "number",
        "PassengerId": "number",
        "Pclass": "number",
        "SibSp": "number",
    }
    response = dtype.patch(
        "/fieldtypes/titanic_testing_projection", json=dict(types)
    )
    assert response.status_code == 200, response.get_data()
    types["Survived"] = "number"
    response = dtype.patch(
        "/fieldtypes/titanic_training_projection", json=types
    )
    assert response.status_code == 200, response.get_data()

    response = models.post(
        "/models",
        json={
            "training_filename": "titanic_training_projection",
            "test_filename": "titanic_testing_projection",
            "preprocessor_code": preprocessor_code,
            "classificators_list": classifiers,
        },
    )
    assert response.status_code == 201, response.get_data()

    out = {}
    for clf in classifiers:
        name = f"titanic_testing_projection_prediction_{clf}"
        meta = json.loads(
            db.get(f"/files/{name}?skip=0&limit=1&query={{}}").get_data()
        )["result"][0]
        out[clf] = meta
    return out


@pytest.mark.integration
def test_documented_walkthrough_runs_verbatim():
    """The published walkthrough end to end: verbatim preprocessor, all
    five classifiers, documented metadata shape, and the documented
    run's stable quality signature (leak classifiers ~1.0, NB the weak
    learner — docs/database_api.md:76-83 shows NB at 0.7035)."""
    results = _drive_walkthrough(
        PREPROCESSING_CODE, ["lr", "dt", "gb", "rf", "nb"]
    )
    for clf, meta in results.items():
        # documented prediction-metadata shape: strings for F1/accuracy,
        # float fit_time, classificator initials
        assert meta["classificator"] == clf
        assert isinstance(meta["F1"], str) and isinstance(meta["accuracy"], str)
        assert isinstance(meta["fit_time"], float)
        accuracy = float(meta["accuracy"])
        f1 = float(meta["F1"])
        assert 0.0 <= f1 <= 1.0
        if clf == "nb":
            # multinomial NB swamped by PassengerId mass — the weak
            # classifier of the documented run (published: 0.7035); its
            # exact value depends on the original file's id/survival
            # interleaving, so a band is asserted, not the point value
            assert 0.60 <= accuracy <= 0.97, accuracy
        else:
            # label leaked into the features: near-perfect separation
            assert accuracy >= 0.95, (clf, accuracy)
    nb_accuracy = float(results["nb"]["accuracy"])
    others = min(
        float(results[c]["accuracy"]) for c in ("lr", "dt", "gb", "rf")
    )
    assert nb_accuracy < others, "NB must be the weak learner, as published"


@pytest.mark.integration
def test_clean_pipeline_matches_canonical_titanic_quality():
    """Leak removed: every classifier must land in the canonical
    Titanic accuracy band (the reproducible quality-parity anchor —
    engineered Titanic features support ~0.75-0.90 holdout accuracy
    across classical model families)."""
    results = _drive_walkthrough(
        CLEAN_PREPROCESSING_CODE, ["lr", "dt", "gb", "rf", "nb"]
    )
    for clf, meta in results.items():
        accuracy = float(meta["accuracy"])
        assert 0.70 <= accuracy <= 0.95, (clf, accuracy)
