"""Chaos suite: the replicated store's failover invariants under
injected faults (docs/replication.md).

What is asserted end to end: a partition's minority primary suspends
writes (503 + Retry-After) while reads keep serving and the majority
follower wins a quorum election — no dual-primary instant; a takeover's
loss window is measured and reported (promotion response, /health,
/metrics); torn wire chunks are retried in place; WAL pollers always
terminate; rev-keyed devcache entries never serve pre-failover content;
and (slow, subprocess) a kill-primary-mid-ingest completes with zero
lost acknowledged writes under sync replication."""

import json
import os
import socket
import subprocess
import sys
import time

import pytest
import requests

from learningorchestra_tpu.core.arbiter import serve as serve_arbiter
from learningorchestra_tpu.core.store import ROW_ID, InMemoryStore
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    ReplicationClient,
    StoreUnavailableError,
    create_store_app,
    serve,
)
from learningorchestra_tpu.testing import faults
from learningorchestra_tpu.utils.web import ServerThread

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(predicate, timeout=15.0, message="condition", tick=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(tick)
    raise AssertionError(f"timed out waiting for {message}")


class TestFaultSpecs:
    def test_spec_parsing_round_trips(self):
        fault = faults.parse_spec("store.wal.feed", "delay:0.25@3")
        assert fault.action == "delay" and fault.arg == 0.25
        assert fault.count == 3
        fault = faults.parse_spec("store.wire.mutate", "kill:5")
        assert fault.action == "kill" and fault.arg == 5.0
        fault = faults.parse_spec("store.wire.read_chunk", "torn")
        assert fault.count == 1  # torn defaults to one corrupt chunk

    @pytest.mark.parametrize(
        "point,spec",
        [
            ("no.such.point", "error"),
            ("store.net", "explode"),
            ("store.net", "delay"),  # delay needs seconds
            ("store.net", "delay:-1"),
            ("store.net", "error@0"),
            ("store.net", "kill:0"),
            ("store.net", "kill@2"),  # kill takes :nth, not @n
            ("store.net", "error:3"),  # error takes no ':' argument
        ],
    )
    def test_malformed_specs_raise(self, point, spec):
        with pytest.raises(ValueError):
            faults.parse_spec(point, spec)

    def test_validate_env_rejects_unknown_point(self):
        with pytest.raises(ValueError, match="no such fault point"):
            faults.validate_env({"LO_FAULT_STORE_WIRE_TYPO": "error"})
        with pytest.raises(ValueError, match="unknown action"):
            faults.validate_env({"LO_FAULT_STORE_NET": "explode"})
        assert faults.validate_env(
            {"LO_FAULT_STORE_NET": "error@2", "UNRELATED": "x"}
        ) == {"store.net": "error@2"}

    def test_error_budget_and_where_matching(self):
        faults.install("store.net", "error@2", where={"me": "P"})
        with pytest.raises(faults.FaultInjected):
            faults.fire("store.net", me="P", url="u")
        faults.fire("store.net", me="F", url="u")  # other node unaffected
        with pytest.raises(faults.FaultInjected):
            faults.fire("store.net", me="P", url="u")
        faults.fire("store.net", me="P", url="u")  # budget spent

    def test_torn_consumes_budget(self):
        faults.install("store.wire.read_chunk", "torn@1")
        assert faults.torn("store.wire.read_chunk") is True
        assert faults.torn("store.wire.read_chunk") is False
        assert faults.torn("store.wal.feed") is False  # other point

    def test_invalid_env_disarms_instead_of_failing_every_hit(
        self, monkeypatch, capsys
    ):
        """fire() runs inside production handlers: a typo'd knob that
        slipped past the entry-point preflights must warn once and arm
        nothing — never turn every mutation into an error."""
        monkeypatch.setenv("LO_FAULT_STORE_WIRE_MUTTE", "kill:8")  # typo
        faults.reset()
        faults.fire("store.wire.mutate")  # must not raise
        faults.fire("store.wire.mutate")
        assert "ignoring invalid LO_FAULT_*" in capsys.readouterr().err


class TestArbiterVotes:
    def test_grant_is_idempotent_after_term_observation(self):
        """A candidate whose grant response was lost retries the
        identical request — the arbiter's observed-term bump must not
        burn the vote the retry is reading back."""
        from learningorchestra_tpu.core.arbiter import create_arbiter_app

        state = {}
        client = create_arbiter_app(state).test_client()
        first = client.post("/vote", json={"term": 5, "candidate": "F"})
        assert first.get_json()["granted"] is True
        retry = client.post("/vote", json={"term": 5, "candidate": "F"})
        assert retry.get_json()["granted"] is True  # idempotent re-ask
        rival = client.post("/vote", json={"term": 5, "candidate": "X"})
        assert rival.get_json()["granted"] is False  # one vote per term
        stale = client.post("/vote", json={"term": 4, "candidate": "X"})
        assert stale.get_json()["granted"] is False
        newer = client.post("/vote", json={"term": 6, "candidate": "X"})
        assert newer.get_json()["granted"] is True


class TestTornChunk:
    def test_torn_wire_frame_is_retried_in_place(self):
        """A truncated binary frame (server falling over mid-response)
        must not fail the read OR leave a torn result: the chunk is
        re-fetched with the transport-retry budget."""
        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            store = RemoteStore(f"http://127.0.0.1:{server.port}")
            store.insert_columns(
                "ds", {"a": list(range(100)), "b": [float(i) for i in range(100)]}
            )
            fault = faults.install("store.wire.read_chunk", "torn@1")
            out = store.read_column_arrays("ds", ["a", "b"])
            assert fault.hits >= 1, "the torn fault never fired"
            assert out["a"].tolist() == list(range(100))
            assert out["b"].tolist() == [float(i) for i in range(100)]
        finally:
            server.stop()

    def test_torn_chunks_past_budget_surface(self):
        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            store = RemoteStore(f"http://127.0.0.1:{server.port}")
            store.chunk_retries = 1
            store.insert_columns("ds", {"a": list(range(10))})
            faults.install("store.wire.read_chunk", "torn@10")
            with pytest.raises(Exception):
                store.read_column_arrays("ds", ["a"])
        finally:
            server.stop()


class TestWalLongPoll:
    def test_long_poll_returns_early_when_a_record_lands(self):
        """`GET /wal?wait=` parks a caught-up follower until a record
        lands — the mechanism that keeps sync-repl ack latency at tens
        of milliseconds instead of one poll interval per write."""
        import threading

        store = InMemoryStore(replicate=True)
        server = ServerThread(
            create_store_app(store), "127.0.0.1", 0
        ).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            timer = threading.Timer(
                0.3, lambda: store.insert_one("ds", {ROW_ID: 1})
            )
            timer.start()
            started = time.monotonic()
            feed = requests.get(
                f"{url}/wal",
                params={"epoch": 0, "offset": 0, "wait": 10},
                timeout=30,
            ).json()
            elapsed = time.monotonic() - started
            timer.cancel()
            assert feed["records"], feed
            assert 0.2 <= elapsed < 5, elapsed  # woke on the write
        finally:
            server.stop()

    def test_store_voters_expose_voted_term(self):
        """A store voter that granted a term must advertise it on
        /health (like the arbiter): it is the supersession evidence a
        quorum-holding-but-partitioned old primary relies on in
        topologies with more than one follower."""
        role = {"writable": False, "poller": None}
        client = create_store_app(InMemoryStore(), role).test_client()
        grant = client.post("/vote", json={"term": 7, "candidate": "B"})
        assert grant.get_json()["granted"] is True
        assert client.get("/health").get_json()["voted_term"] == 7

    def test_wal_position_is_atomic_pairing(self):
        store = InMemoryStore(replicate=True)
        store.insert_one("ds", {ROW_ID: 1})
        assert store.wal_position == (0, 1)
        store.compact()
        epoch, length = store.wal_position
        assert epoch == 1 and length >= 1


class TestLandedOkAfterServerError:
    def test_single_url_500_after_apply_then_replay_succeeds(self):
        """A handler that dies AFTER applying (500 back to a
        single-URL client) is as ambiguous as a dropped connection:
        the scheduler-level replay's clean 409 must verify by read and
        succeed instead of aborting the durable ingest."""
        server = ServerThread(
            create_store_app(InMemoryStore()), "127.0.0.1", 0
        ).start()
        try:
            store = RemoteStore(f"http://127.0.0.1:{server.port}")
            faults.install("store.wire.mutate.applied", "error@1")
            with pytest.raises(requests.HTTPError):
                store.insert_one("ds", {ROW_ID: 1, "v": "x"})
            # the write IS on the server; the replay must land as ok
            store.insert_one("ds", {ROW_ID: 1, "v": "x"})
            assert store.count("ds") == 1
        finally:
            server.stop()


class TestQuorumPartition:
    """The fast deterministic partition drill (default selection): the
    minority primary suspends, the majority follower wins the election,
    writes continue on the majority side, and healing demotes the old
    primary — no dual-primary instant observed."""

    def _topology(self):
        p_port, f_port = _free_port(), _free_port()
        p_url = f"http://127.0.0.1:{p_port}"
        f_url = f"http://127.0.0.1:{f_port}"
        arbiter = serve_arbiter("127.0.0.1", 0)
        a_url = f"http://127.0.0.1:{arbiter.port}"
        primary = serve(
            "127.0.0.1",
            p_port,
            replicate=True,
            peers=[f_url],
            arbiters=[a_url],
            node_id="P",
            monitor_tick_s=0.1,
            quorum_grace_s=0.3,
        )
        follower = serve(
            "127.0.0.1",
            f_port,
            primary_url=p_url,
            peers=[p_url],
            arbiters=[a_url],
            auto_promote_s=0.9,
            node_id="F",
            monitor_tick_s=0.1,
        )
        return arbiter, primary, follower, p_url, f_url, a_url

    def test_partition_minority_suspends_majority_promotes(self):
        arbiter, primary, follower, p_url, f_url, _ = self._topology()
        try:
            client = RemoteStore(p_url)
            client.create_collection("ds")
            client.insert_one("ds", {ROW_ID: 1, "v": "before"})
            _wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )

            # partition the PRIMARY's backend traffic both ways: its
            # own probes fail, and anything addressed to it from the
            # backend (the follower's WAL polls, vote requests) fails.
            # Client HTTP stays up — a backend partition does not sever
            # client reach, which is exactly the dual-primary hazard.
            faults.install("store.net", "error", where={"me": "P"})
            faults.install("store.net", "error", where={"url": p_url})

            # No dual-primary instant: sample both sides until the
            # follower promotes; whenever the follower is writable the
            # primary must already be suspended.
            deadline = time.time() + 15
            saw_promotion = False
            while time.time() < deadline:
                f_writable = follower.store_role.get("writable", False)
                p_suspended = primary.store_role.get("suspended", False)
                if f_writable:
                    assert p_suspended, (
                        "dual-primary window: follower writable while "
                        "the minority primary still accepted writes"
                    )
                    saw_promotion = True
                    break
                time.sleep(0.02)
            assert saw_promotion, "follower never promoted with quorum"
            assert follower.store_role["term"] >= 2
            assert arbiter.arbiter_state["voted_for"] == "F"

            # minority side: writes 503 + Retry-After, reads keep serving
            response = requests.post(
                f"{p_url}/c/ds/insert_one",
                json={"document": {ROW_ID: 99, "v": "split"}},
                timeout=5,
            )
            assert response.status_code == 503
            assert response.headers.get("Retry-After")
            assert response.json()["kind"] == "writes_suspended"
            assert requests.get(f"{p_url}/health", timeout=5).json()[
                "suspended"
            ]
            read = requests.post(
                f"{p_url}/c/ds/find",
                json={"query": {}, "skip": 0, "limit": None},
                timeout=5,
            )
            assert read.status_code == 200
            assert len(read.json()["documents"]) == 1
            # a single-URL client surfaces the suspension as the
            # TRANSIENT StoreUnavailableError the scheduler retries
            with pytest.raises(StoreUnavailableError):
                RemoteStore(p_url, failover_timeout=0.2).insert_one(
                    "ds", {ROW_ID: 50, "v": "blocked"}
                )

            # majority side: writes continue
            majority = RemoteStore(f_url)
            majority.insert_one("ds", {ROW_ID: 2, "v": "after"})
            assert majority.count("ds") == 2
            # the takeover terminated the WAL poller (no zombie pollers)
            assert follower.store_role["poller"] is None

            # heal: the old primary demotes to follower of the new one
            # and resyncs the post-failover write
            faults.reset()
            _wait_for(
                lambda: primary.store_role.get("writable") is False,
                message="old primary demotion",
            )
            _wait_for(
                lambda: primary.store.count("ds") == 2,
                message="old primary resync",
            )
            assert follower.store_role["writable"] is True
        finally:
            faults.reset()
            primary.stop()
            follower.stop()
            arbiter.stop()

    def test_asymmetric_partition_cannot_keep_two_writers(self):
        """Only the primary↔follower link fails; BOTH still reach the
        arbiter. The follower legitimately wins self+arbiter and
        promotes — and the old primary, whose voter quorum is still
        numerically intact via the arbiter, must recognize the
        arbiter's higher voted term as supersession and suspend
        instead of staying a second writer."""
        arbiter, primary, follower, p_url, f_url, _ = self._topology()
        try:
            client = RemoteStore(p_url)
            client.insert_columns("ds", {"v": [1]})
            _wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )
            # sever ONLY the P↔F link, both directions
            faults.install(
                "store.net", "error", where={"me": "P", "url": f_url}
            )
            faults.install(
                "store.net", "error", where={"me": "F", "url": p_url}
            )
            _wait_for(
                lambda: follower.store_role.get("writable"),
                message="follower takeover via arbiter",
            )
            # the old primary heard the new term through the arbiter
            _wait_for(
                lambda: primary.store_role.get("suspended"),
                message="old primary suspension on supersession",
            )
            response = requests.post(
                f"{p_url}/c/ds/insert_one",
                json={"document": {ROW_ID: 77}},
                timeout=5,
            )
            assert response.status_code == 503
            # heal: the fence demotes the old primary to the winner
            faults.reset()
            _wait_for(
                lambda: primary.store_role.get("writable") is False,
                message="old primary demotion after heal",
            )
        finally:
            faults.reset()
            primary.stop()
            follower.stop()
            arbiter.stop()

    def test_failed_campaign_without_quorum(self):
        """A follower that cannot assemble a majority (primary AND
        arbiter unreachable) must keep refusing writes — graceful
        degradation, not a blind timer promotion."""
        arbiter, primary, follower, p_url, f_url, a_url = self._topology()
        try:
            # isolate the FOLLOWER: everything it dials fails
            faults.install("store.net", "error", where={"me": "F"})
            time.sleep(2.2)  # several auto-promote windows
            assert follower.store_role["writable"] is False
            with pytest.raises(PermissionError):
                RemoteStore(f_url).insert_one("ds", {ROW_ID: 1})
            # reads still serve on the degraded follower
            assert RemoteStore(f_url).count("ds") == 0
        finally:
            faults.reset()
            primary.stop()
            follower.stop()
            arbiter.stop()


class TestLossWindow:
    def test_takeover_reports_measured_loss_window(self):
        """Delayed WAL shipping: the promotion response, /health, and
        /metrics all report exactly the acknowledged records the
        takeover cost (ROADMAP: failover cost must be visible)."""
        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            follower.replication.stop()  # drive shipping by hand
            poller = ReplicationClient(
                follower.store,
                f"http://127.0.0.1:{primary.port}",
                batch=2,  # ship at most 2 records per poll
            )
            client = RemoteStore(f"http://127.0.0.1:{primary.port}")
            client.create_collection("ds")
            for i in range(1, 5):
                client.insert_one("ds", {ROW_ID: i, "v": i})
            poller.poll_once()  # resolves the epoch (resync)
            poller.poll_once()  # applies 2 of the 5 records
            assert poller.lag == 3
            follower.store_role["poller"] = poller

            response = requests.post(
                f"http://127.0.0.1:{follower.port}/promote", timeout=10
            ).json()
            loss = response["loss_window"]
            assert loss["records"] == 3
            assert loss["primary_wal_length"] == 5
            assert loss["applied_offset"] == 2
            assert response["caught_up"] is False

            health = requests.get(
                f"http://127.0.0.1:{follower.port}/health", timeout=5
            ).json()
            assert health["loss_window"]["records"] == 3

            metrics = requests.get(
                f"http://127.0.0.1:{follower.port}/metrics", timeout=5
            ).text
            samples = [
                line
                for line in metrics.splitlines()
                if line.startswith("lo_store_loss_window{")
            ]
            assert any(line.endswith(" 3") for line in samples), samples
        finally:
            primary.stop()
            follower.stop()

    def test_follower_health_reports_replication_lag(self):
        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            client = RemoteStore(f"http://127.0.0.1:{primary.port}")
            client.insert_columns("ds", {"a": [1, 2, 3]})
            _wait_for(
                lambda: follower.store.count("ds") == 3,
                message="follower sync",
            )
            health = requests.get(
                f"http://127.0.0.1:{follower.port}/health", timeout=5
            ).json()
            assert health["replication"]["lag"] == 0
            assert health["replication"]["caught_up"] is True
        finally:
            primary.stop()
            follower.stop()


class TestSyncReplication:
    def test_ack_waits_for_follower_and_flags_timeouts(self, monkeypatch):
        monkeypatch.setenv("LO_REPL_INTERVAL_S", "0.05")
        primary = serve(
            "127.0.0.1",
            0,
            replicate=True,
            sync_repl=True,
            ack_timeout_s=0.3,
        )
        p_url = f"http://127.0.0.1:{primary.port}"
        follower = None
        try:
            # no follower yet: the ack wait times out and the write is
            # FLAGGED, not silently majority-acknowledged
            started = time.monotonic()
            response = requests.post(
                f"{p_url}/c/ds/insert_one",
                json={"document": {ROW_ID: 1, "v": 1}},
                timeout=10,
            )
            assert time.monotonic() - started >= 0.3
            assert response.json().get("replicated") is False
            metrics = requests.get(f"{p_url}/metrics", timeout=5).text
            assert any(
                line.startswith("lo_store_unreplicated_acks{")
                and line.endswith(" 1")
                for line in metrics.splitlines()
            )

            follower = serve("127.0.0.1", 0, primary_url=p_url)
            _wait_for(
                lambda: follower.store.count("ds") == 1,
                message="follower sync",
            )
            # with a live follower the ack confirms replication: no flag
            response = requests.post(
                f"{p_url}/c/ds/insert_one",
                json={"document": {ROW_ID: 2, "v": 2}},
                timeout=10,
            )
            assert "replicated" not in response.json()
            assert follower.store.count("ds") >= 1
        finally:
            primary.stop()
            if follower is not None:
                follower.stop()


class TestDevcacheAcrossFailover:
    def test_rev_keyed_entries_never_serve_pre_failover_content(self):
        """The devcache's rev probe + the store's per-boot random rev
        base guarantee a post-failover read can't be served from a
        pre-failover cache entry even though the collection name is
        unchanged."""
        from learningorchestra_tpu.core import devcache

        devcache.reset_global_devcache()
        primary = serve("127.0.0.1", 0, replicate=True)
        follower = serve(
            "127.0.0.1",
            0,
            primary_url=f"http://127.0.0.1:{primary.port}",
        )
        try:
            store = RemoteStore(
                f"http://127.0.0.1:{primary.port},"
                f"http://127.0.0.1:{follower.port}",
                failover_timeout=20,
            )
            store.insert_columns("ds", {"a": [1.0, 2.0]})
            _wait_for(
                lambda: follower.store.count("ds") == 2,
                message="follower sync",
            )
            table = devcache.dataset_table(store, "ds", fields=["a"])
            assert table.columns["a"].tolist() == [1.0, 2.0]

            primary.stop()
            requests.post(
                f"http://127.0.0.1:{follower.port}/promote", timeout=10
            )
            survivor = RemoteStore(f"http://127.0.0.1:{follower.port}")
            survivor.set_column("ds", "a", [7.0, 8.0])

            again = devcache.dataset_table(store, "ds", fields=["a"])
            assert again.columns["a"].tolist() == [7.0, 8.0], (
                "devcache served pre-failover content after a takeover"
            )
        finally:
            devcache.reset_global_devcache()
            primary.stop()
            follower.stop()


def _spawn(env_extra, *argv):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, *argv],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
    )


def _wait_line(process, marker, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = process.stdout.readline()
        if not line:
            if process.poll() is not None:
                raise RuntimeError(f"process died (rc={process.returncode})")
            time.sleep(0.05)
            continue
        if marker in line:
            return line.strip()
    raise TimeoutError(f"no {marker!r} line within {timeout}s")


@pytest.mark.slow
def test_kill_primary_mid_ingest_zero_lost_acked_writes(tmp_path):
    """THE failover drill (ROADMAP: 'failover with zero lost
    acknowledged writes'): a real primary process is killed by an armed
    fault mid write burst — after the write applied but before the ack.
    Under sync replication every acknowledged write is already on the
    follower, the client rides the quorum takeover via its landed-ok
    retry machinery, and the full ingest lands with nothing lost."""
    p_port, f_port, a_port = _free_port(), _free_port(), _free_port()
    p_url = f"http://127.0.0.1:{p_port}"
    f_url = f"http://127.0.0.1:{f_port}"
    a_url = f"http://127.0.0.1:{a_port}"
    processes = []
    try:
        arbiter = _spawn(
            {"LO_ARBITER_PORT": str(a_port)},
            "-m",
            "learningorchestra_tpu.core.arbiter",
        )
        processes.append(arbiter)
        _wait_line(arbiter, "store arbiter on ")
        shared = {
            "LO_ARBITERS": a_url,
            "LO_REPL_INTERVAL_S": "0.05",
            "LO_STORE_MONITOR_TICK_S": "0.2",
        }
        primary = _spawn(
            {
                **shared,
                "LO_STORE_PORT": str(p_port),
                "LO_DATA_DIR": str(tmp_path / "p"),
                "LO_REPLICATE": "1",
                "LO_PEERS": f_url,
                "LO_NODE_ID": "P",
                "LO_STORE_SYNC_REPL": "1",
                "LO_STORE_ACK_TIMEOUT_S": "5",
                # die DURING the 8th mutation: applied, never acked
                "LO_FAULT_STORE_WIRE_MUTATE_APPLIED": "kill:8",
            },
            "-m",
            "learningorchestra_tpu.core.store_service",
        )
        processes.append(primary)
        _wait_line(primary, "store server on ")
        follower = _spawn(
            {
                **shared,
                "LO_STORE_PORT": str(f_port),
                "LO_DATA_DIR": str(tmp_path / "f"),
                "LO_PRIMARY_URL": p_url,
                "LO_PEERS": p_url,
                "LO_NODE_ID": "F",
                "LO_AUTO_PROMOTE_S": "1",
            },
            "-m",
            "learningorchestra_tpu.core.store_service",
        )
        processes.append(follower)
        _wait_line(follower, "store server on ")

        client = RemoteStore(f"{p_url},{f_url}", failover_timeout=45)
        client.create_collection("ds")  # mutation hit 1
        acked = []
        for i in range(1, 21):
            # explicit ids: the idempotent, landed-ok-retryable shape
            client.insert_one("ds", {ROW_ID: i, "v": f"row{i}"})
            acked.append(i)

        # the fault really killed the primary process
        primary.wait(timeout=30)
        assert primary.returncode == 137

        survivor = RemoteStore(f_url)
        health = requests.get(f"{f_url}/health", timeout=5).json()
        assert health["writable"] is True
        assert health["term"] >= 2
        assert health.get("loss_window") is not None
        # ZERO lost acknowledged writes: every acked row is present
        # with its content on the surviving primary
        rows = {
            d[ROW_ID]: d["v"] for d in survivor.find("ds", {})
        }
        for i in acked:
            assert rows.get(i) == f"row{i}", f"acked row {i} lost"
        assert survivor.count("ds") == 20
    finally:
        for process in processes:
            process.terminate()
        for process in processes:
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                process.kill()
