"""Checkpoint/resume: every fitted model round-trips through disk."""

import os

import numpy as np
import pytest

from learningorchestra_tpu.ml.base import make_classifier
from learningorchestra_tpu.ml.checkpoint import load_model, save_model
from learningorchestra_tpu.utils.profiling import PhaseTimer


@pytest.fixture()
def data(rng):
    X = rng.normal(size=(300, 5))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


class TestCheckpoint:
    @pytest.mark.parametrize("name", ["lr", "nb", "dt", "rf", "gb"])
    def test_roundtrip_predictions_identical(self, name, data, tmp_path):
        X, y = data
        X_fit = np.abs(X) if name == "nb" else X
        model = make_classifier(name).fit(X_fit, y)
        path = str(tmp_path / f"{name}.npz")
        save_model(model, path)
        restored = load_model(path)
        np.testing.assert_array_equal(
            model.predict(X_fit), restored.predict(X_fit)
        )
        np.testing.assert_allclose(
            model.predict_proba(X_fit), restored.predict_proba(X_fit), atol=1e-6
        )

    def test_unknown_type_raises(self, tmp_path):
        with pytest.raises(TypeError):
            save_model(object(), str(tmp_path / "x.npz"))


class TestCheckpointWiring:
    """Checkpoints on the PRODUCT path: build_model persists every
    fitted model, and a fresh service instance (the 'killed and
    restarted' process) reproduces predictions from the artifact alone —
    the durability the reference lacks (model_builder.py:232-247)."""

    def _ingest(self, store, titanic_csv):
        from learningorchestra_tpu.core.ingest import (
            ingest_csv,
            write_ingest_metadata,
        )
        from learningorchestra_tpu.ops.dtype import convert_field_types

        for name in ("ck_train", "ck_test"):
            write_ingest_metadata(store, name, titanic_csv)
            ingest_csv(store, name, titanic_csv)
            convert_field_types(
                store,
                name,
                {
                    f: "number"
                    for f in (
                        "PassengerId", "Survived", "Pclass", "Age",
                        "SibSp", "Parch", "Fare",
                    )
                },
            )

    def test_kill_and_reload_reproduces_predictions(
        self, store, titanic_csv, tmp_path
    ):
        from learningorchestra_tpu.services import model_builder
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        self._ingest(store, titanic_csv)
        models_dir = str(tmp_path / "models")

        app = model_builder.create_app(store, models_dir=models_dir)
        client = app.test_client()
        response = client.post(
            "/models",
            json={
                "training_filename": "ck_train",
                "test_filename": "ck_test",
                "preprocessor_code": DOCUMENTED_PREPROCESSOR,
                "classificators_list": ["lr"],
            },
        )
        assert response.status_code == 201

        name = "ck_test_prediction_lr"
        metadata = store.find_one(name, {"classificator": "lr"})
        assert metadata["model_checkpoint"] == os.path.join(
            models_dir, name + ".model"
        )
        assert os.path.isfile(metadata["model_checkpoint"])
        assert "checkpoint" in metadata["timings"]
        original = store.read_columns(name, ["prediction"])["prediction"]

        # The restarted process: a brand-new app over the same volume.
        reloaded = model_builder.create_app(
            store, models_dir=models_dir
        ).test_client()
        listing = reloaded.get("/models").get_json()["result"]
        assert name in listing
        info = reloaded.get(f"/models/{name}").get_json()["result"]
        assert info["kind"] == "logistic" and info["size_bytes"] > 0

        response = reloaded.post(
            f"/models/{name}/predictions",
            json={
                "training_filename": "ck_train",
                "test_filename": "ck_test",
                "preprocessor_code": DOCUMENTED_PREPROCESSOR,
                "prediction_filename": "ck_reloaded",
            },
        )
        assert response.status_code == 201
        reproduced = store.read_columns("ck_reloaded", ["prediction"])[
            "prediction"
        ]
        assert reproduced == original
        metadata = store.find_one("ck_reloaded", {"_id": 0})
        assert "fit" not in metadata["timings"]  # no refit happened

    def test_predict_missing_model_404(self, store, tmp_path):
        from learningorchestra_tpu.services import model_builder

        client = model_builder.create_app(
            store, models_dir=str(tmp_path)
        ).test_client()
        response = client.post(
            "/models/nope/predictions",
            json={
                "training_filename": "t",
                "test_filename": "x",
                "preprocessor_code": "",
                "prediction_filename": "y",
            },
        )
        assert response.status_code == 404
        assert client.get("/models/nope").status_code == 404
        assert client.get("/models").get_json()["result"] == []


class TestPhaseTimer:
    def test_accumulates(self):
        timer = PhaseTimer()
        with timer.phase("a"):
            pass
        with timer.phase("a"):
            pass
        with timer.phase("b"):
            pass
        assert set(timer.timings) == {"a", "b"}
        assert timer.as_metadata()["a"] >= 0

    def test_builder_records_timings(self, store, titanic_csv):
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
        from learningorchestra_tpu.ml.builder import build_model
        from learningorchestra_tpu.ops.dtype import convert_field_types
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        for name in ("t_train", "t_test"):
            write_ingest_metadata(store, name, titanic_csv)
            ingest_csv(store, name, titanic_csv)
            convert_field_types(
                store,
                name,
                {
                    f: "number"
                    for f in (
                        "PassengerId", "Survived", "Pclass", "Age",
                        "SibSp", "Parch", "Fare",
                    )
                },
            )
        results = build_model(
            store, "t_train", "t_test", DOCUMENTED_PREPROCESSOR, ["nb"]
        )
        timings = results[0]["timings"]
        # "evaluate" covers the fused metrics+prediction pass (one
        # forward, one transfer — ml/base.evaluate_predict); a separate
        # "predict" phase appears only when there is no eval split
        assert {"fit", "evaluate", "write"} <= set(timings)

    def test_trace_dir_written(self, store, titanic_csv, tmp_path, monkeypatch):
        """LO_TRACE_DIR captures a device profile of the build fan-out
        (TensorBoard/Perfetto-loadable), one dir per build."""
        from learningorchestra_tpu.ml.builder import build_model
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        TestCheckpointWiring()._ingest(store, titanic_csv)
        trace_root = tmp_path / "traces"
        monkeypatch.setenv("LO_TRACE_DIR", str(trace_root))
        build_model(
            store, "ck_train", "ck_test", DOCUMENTED_PREPROCESSOR, ["nb"]
        )
        captures = list(trace_root.glob("build_ck_test_*"))
        assert len(captures) == 1 and captures[0].is_dir()
        assert any(p.is_file() for p in captures[0].rglob("*"))

    def test_unwritable_trace_root_runs_untraced_and_releases_lock(
        self, store, titanic_csv, tmp_path, monkeypatch
    ):
        """Tracing is observability: a bad LO_TRACE_DIR must neither
        500 the build nor leak _TRACE_LOCK (which would silently
        disable tracing for the life of the process)."""
        from learningorchestra_tpu.ml import builder

        TestCheckpointWiring()._ingest(store, titanic_csv)
        monkeypatch.setenv("LO_TRACE_DIR", str(tmp_path / "traces"))

        def boom(root, name):
            raise PermissionError(13, "read-only volume", root)

        monkeypatch.setattr(builder, "_next_trace_dir", boom)
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        results = builder.build_model(
            store, "ck_train", "ck_test", DOCUMENTED_PREPROCESSOR, ["nb"]
        )
        assert results  # built fine, just untraced
        assert builder._TRACE_LOCK.acquire(blocking=False)  # not leaked
        builder._TRACE_LOCK.release()

    def test_next_trace_dir_reserves_by_creating(self, tmp_path):
        """Claiming a capture dir must create it: an exists() probe
        would let two processes sharing LO_TRACE_DIR pick the same
        name."""
        from learningorchestra_tpu.ml.builder import _next_trace_dir

        first = _next_trace_dir(str(tmp_path), "t")
        second = _next_trace_dir(str(tmp_path), "t")
        assert first != second
        assert os.path.isdir(first) and os.path.isdir(second)

    def test_roundtrip_with_non_npz_extension(self, data, tmp_path):
        X, y = data
        model = make_classifier("nb").fit(np.abs(X), y)
        path = str(tmp_path / "model.ckpt")
        save_model(model, path)
        restored = load_model(path)
        np.testing.assert_array_equal(
            model.predict(np.abs(X)), restored.predict(np.abs(X))
        )
