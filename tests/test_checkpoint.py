"""Checkpoint/resume: every fitted model round-trips through disk."""

import numpy as np
import pytest

from learningorchestra_tpu.ml.base import make_classifier
from learningorchestra_tpu.ml.checkpoint import load_model, save_model
from learningorchestra_tpu.utils.profiling import PhaseTimer


@pytest.fixture()
def data(rng):
    X = rng.normal(size=(300, 5))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(int)
    return X, y


class TestCheckpoint:
    @pytest.mark.parametrize("name", ["lr", "nb", "dt", "rf", "gb"])
    def test_roundtrip_predictions_identical(self, name, data, tmp_path):
        X, y = data
        X_fit = np.abs(X) if name == "nb" else X
        model = make_classifier(name).fit(X_fit, y)
        path = str(tmp_path / f"{name}.npz")
        save_model(model, path)
        restored = load_model(path)
        np.testing.assert_array_equal(
            model.predict(X_fit), restored.predict(X_fit)
        )
        np.testing.assert_allclose(
            model.predict_proba(X_fit), restored.predict_proba(X_fit), atol=1e-6
        )

    def test_unknown_type_raises(self, tmp_path):
        with pytest.raises(TypeError):
            save_model(object(), str(tmp_path / "x.npz"))


class TestPhaseTimer:
    def test_accumulates(self):
        timer = PhaseTimer()
        with timer.phase("a"):
            pass
        with timer.phase("a"):
            pass
        with timer.phase("b"):
            pass
        assert set(timer.timings) == {"a", "b"}
        assert timer.as_metadata()["a"] >= 0

    def test_builder_records_timings(self, store, titanic_csv):
        from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
        from learningorchestra_tpu.ml.builder import build_model
        from learningorchestra_tpu.ops.dtype import convert_field_types
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        for name in ("t_train", "t_test"):
            write_ingest_metadata(store, name, titanic_csv)
            ingest_csv(store, name, titanic_csv)
            convert_field_types(
                store,
                name,
                {
                    f: "number"
                    for f in (
                        "PassengerId", "Survived", "Pclass", "Age",
                        "SibSp", "Parch", "Fare",
                    )
                },
            )
        results = build_model(
            store, "t_train", "t_test", DOCUMENTED_PREPROCESSOR, ["nb"]
        )
        timings = results[0]["timings"]
        assert {"fit", "evaluate", "predict"} <= set(timings)

    def test_roundtrip_with_non_npz_extension(self, data, tmp_path):
        X, y = data
        model = make_classifier("nb").fit(np.abs(X), y)
        path = str(tmp_path / "model.ckpt")
        save_model(model, path)
        restored = load_model(path)
        np.testing.assert_array_equal(
            model.predict(np.abs(X)), restored.predict(np.abs(X))
        )
