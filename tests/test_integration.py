"""Full-stack integration: all seven services on their real ports, driven
by the API-compatible client — the reference's Titanic walkthrough
(reference learning_orchestra_client/readme.md "usage example";
SURVEY.md §4 calls it the de-facto integration test)."""

import json

import pytest

import learningorchestra_tpu.client as lo_client
from learningorchestra_tpu.client import (
    Context,
    DatabaseApi,
    DataTypeHandler,
    Histogram,
    Model,
    Pca,
    Projection,
)
from learningorchestra_tpu.core.store import InMemoryStore
from learningorchestra_tpu.services.runner import start_all
from tests.test_frame import DOCUMENTED_PREPROCESSOR


PORT_ATTRS = {
    5000: (DatabaseApi, "DATABASE_API_PORT"),
    5001: (Projection, "PROJECTION_PORT"),
    5002: (Model, "MODEL_BUILDER_PORT"),
    5003: (DataTypeHandler, "DATA_TYPE_HANDLER_PORT"),
    5004: (Histogram, "HISTOGRAM_PORT"),
    5005: (lo_client.Tsne, "TSNE_PORT"),
    5006: (Pca, "PCA_PORT"),
}


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    store = InMemoryStore()
    images_dir = str(tmp_path_factory.mktemp("images"))
    # Ephemeral ports: the suite must not depend on 5000-5006 being free
    # (a previously running stack would otherwise error the whole module).
    store, servers = start_all(store, images_dir, ephemeral=True)
    saved = {}
    for server in servers:
        cls, attr = PORT_ATTRS[server.canonical_port]
        saved[(cls, attr)] = getattr(cls, attr)
        setattr(cls, attr, str(server.port))
    saved_wait = lo_client.AsyncronousWait.WAIT_TIME
    lo_client.AsyncronousWait.WAIT_TIME = 0.05  # fast polls in tests
    Context("127.0.0.1")
    yield store
    lo_client.AsyncronousWait.WAIT_TIME = saved_wait
    for (cls, attr), value in saved.items():
        setattr(cls, attr, value)
    for server in servers:
        server.stop()


@pytest.mark.integration
def test_titanic_walkthrough(stack, titanic_csv):
    database = DatabaseApi()

    result = database.create_file("titanic_train", titanic_csv, pretty_response=False)
    assert result == {"result": "file_created"}
    result = database.create_file("titanic_test", titanic_csv, pretty_response=False)
    assert result == {"result": "file_created"}

    projection = Projection()
    fields = [
        "PassengerId", "Survived", "Pclass", "Name", "Sex",
        "Age", "SibSp", "Parch", "Fare", "Embarked",
    ]
    result = projection.create_projection(
        "titanic_train", "train_proj", list(fields), pretty_response=False
    )
    assert result == {"result": "created_file"}
    result = projection.create_projection(
        "titanic_test", "test_proj", list(fields), pretty_response=False
    )
    assert result == {"result": "created_file"}

    handler = DataTypeHandler()
    numeric = {
        f: "number"
        for f in ("PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare")
    }
    for name in ("train_proj", "test_proj"):
        result = handler.change_file_type(name, dict(numeric), pretty_response=False)
        assert result == {"result": "file_changed"}

    histogram = Histogram()
    result = histogram.create_histogram(
        "train_proj", "train_hist", ["Sex", "Pclass"], pretty_response=False
    )
    assert result == {"result": "created_file"}
    histogram_doc = next(stack.find("train_hist", {"_id": 1}))
    assert {e["_id"]: e["count"] for e in histogram_doc["Sex"]} == {
        "male": 5,
        "female": 3,
    }

    model = Model()
    result = model.create_model(
        "train_proj",
        "test_proj",
        DOCUMENTED_PREPROCESSOR,
        ["lr", "nb"],
        pretty_response=False,
    )
    assert result == {"result": "created_file"}

    for name in ("lr", "nb"):
        collection = f"test_proj_prediction_{name}"
        meta = stack.find_one(collection, {"_id": 0})
        assert meta["classificator"] == name
        assert float(meta["accuracy"]) >= 0
        rows = database.read_file(collection, limit=10, pretty_response=False)
        predictions = rows["result"][1:]
        assert predictions and "prediction" in predictions[0]

    pca = Pca()
    result = pca.create_image_plot(
        "train_pca", "train_proj", label_name="Sex", pretty_response=False
    )
    assert result == {"result": "created_file"}
    filenames = pca.read_image_plot_filenames(pretty_response=False)
    assert filenames == {"result": ["train_pca.png"]}

    # error semantics through the client: 4xx raises with the message
    with pytest.raises(Exception, match="duplicate_file"):
        database.create_file("titanic_train", titanic_csv, pretty_response=False)


@pytest.mark.integration
def test_pretty_response_returns_json_string(stack, titanic_csv):
    database = DatabaseApi()
    listing = database.read_resume_files(pretty_response=True)
    assert isinstance(listing, str)
    assert "result" in json.loads(listing)


@pytest.mark.integration
def test_client_pipeline_yields_one_stitched_trace(stack, titanic_csv):
    """Fleet observability acceptance: a client-driven ingest →
    projection → histogram run, correlated by the ONE cid the SDK
    Context mints, answers a single stitched Chrome trace at
    GET /traces/<cid> with process rows from at least three services."""
    import requests

    context = Context("127.0.0.1")  # re-mint: one cid per pipeline run
    cid = context.correlation_id
    assert cid and lo_client.correlation_id == cid

    database = DatabaseApi()
    result = database.create_file(
        "stitch_train", titanic_csv, pretty_response=False
    )
    assert result == {"result": "file_created"}
    projection = Projection()
    result = projection.create_projection(
        "stitch_train", "stitch_proj",
        ["PassengerId", "Survived", "Pclass", "Sex"],
        pretty_response=False,
    )
    assert result == {"result": "created_file"}
    histogram = Histogram()
    result = histogram.create_histogram(
        "stitch_proj", "stitch_hist", ["Sex"], pretty_response=False
    )
    assert result == {"result": "created_file"}

    base = f"{lo_client.cluster_url}:{DatabaseApi.DATABASE_API_PORT}"
    # every SDK request rides the minted cid; the middleware echoes it
    probe = requests.get(
        f"{base}/health", headers=lo_client._correlation_headers(),
        timeout=5,
    )
    assert probe.headers.get("X-Correlation-Id") == cid

    response = requests.get(f"{base}/traces/{cid}", timeout=10)
    assert response.status_code == 200
    trace = response.json()
    assert trace["otherData"]["correlation_id"] == cid
    processes = trace["otherData"]["processes"]
    services = {proc.split("@", 1)[0] for proc in processes.values()}
    assert {"database_api", "projection", "histogram"} <= services
    assert len(processes) >= 3
    # golden layout: one M process_name row per group, X events
    # anchored to the shared t0
    named = {
        event["args"]["name"]
        for event in trace["traceEvents"]
        if event.get("ph") == "M" and event["name"] == "process_name"
    }
    assert named == set(processes.values())
    complete = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert complete
    assert min(event["ts"] for event in complete) == 0.0
    assert all(event["dur"] >= 0 for event in complete)
