"""DataFrame facade: expressions, verbs, feature stages, and the full
documented preprocessor example running verbatim."""

import numpy as np
import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.table import ColumnTable
from learningorchestra_tpu.frame import (
    DataFrame,
    StringIndexer,
    VectorAssembler,
    col,
    regexp_extract,
    when,
)
from learningorchestra_tpu.frame.pyspark_compat import run_preprocessor
from learningorchestra_tpu.ops.dtype import convert_field_types


@pytest.fixture()
def df():
    return DataFrame.from_table(
        ColumnTable.from_lists(
            {
                "name": ["Braund, Mr. Owen", "Cumings, Mrs. John", None],
                "age": [22.0, None, 26.0],
                "fare": [7.25, 71.28, 7.92],
            }
        )
    )


class TestExpressions:
    def test_arithmetic(self, df):
        out = df.withColumn("double_fare", col("fare") * 2 + 1)
        np.testing.assert_allclose(
            out._column("double_fare"), [15.5, 143.56, 16.84]
        )

    def test_when_isnull_otherwise(self, df):
        out = df.withColumn(
            "age", when(df["age"].isNull(), 99).otherwise(df["age"])
        )
        np.testing.assert_allclose(out._column("age"), [22, 99, 26])

    def test_equality_with_null_is_false(self, df):
        out = df.withColumn("is_b", when(df["name"] == "Braund, Mr. Owen", 1).otherwise(0))
        np.testing.assert_allclose(out._column("is_b"), [1, 0, 0])

    def test_regexp_extract(self, df):
        out = df.withColumn(
            "title", regexp_extract(col("name"), r"([A-Za-z]+)\.", 1)
        )
        assert list(out._column("title")) == ["Mr", "Mrs", None]

    def test_compound_condition(self, df):
        out = df.withColumn(
            "flag",
            when((df["fare"] > 7) & (df["age"].isNull()), 1).otherwise(0),
        )
        np.testing.assert_allclose(out._column("flag"), [0, 1, 0])


class TestVerbs:
    def test_rename_drop_columns(self, df):
        out = df.withColumnRenamed("fare", "price").drop("name")
        assert out.columns == ["age", "price"]

    def test_na_fill_dict(self, df):
        out = df.na.fill({"age": 0, "name": "unknown"})
        assert out._column("age")[1] == 0
        assert out._column("name")[2] == "unknown"

    def test_replace_list(self, df):
        out = df.replace(["Braund, Mr. Owen"], ["X"])
        assert out._column("name")[0] == "X"

    def test_random_split_deterministic(self, df):
        big = DataFrame({"x": np.arange(1000, dtype=np.float64)})
        a1, b1 = big.randomSplit([0.8, 0.2], seed=33)
        a2, b2 = big.randomSplit([0.8, 0.2], seed=33)
        assert a1.count() == a2.count() and b1.count() == b2.count()
        assert a1.count() + b1.count() == 1000
        assert abs(a1.count() - 800) < 60

    def test_first_and_schema(self, df):
        row = df.first()
        assert row["name"] == "Braund, Mr. Owen"
        assert row["age"] == 22.0
        assert df.schema.names == ["name", "age", "fare"]


class TestFeatureStages:
    def test_string_indexer_frequency_desc(self):
        df = DataFrame.from_table(
            ColumnTable.from_lists({"c": ["b", "a", "b", "c", "b", "a"]})
        )
        model = StringIndexer(inputCol="c", outputCol="c_index").fit(df)
        assert model.labels == ["b", "a", "c"]
        out = model.transform(df)
        np.testing.assert_allclose(out._column("c_index"), [0, 1, 0, 2, 0, 1])

    def test_string_indexer_unseen_errors(self):
        df = DataFrame.from_table(ColumnTable.from_lists({"c": ["a", "b"]}))
        model = StringIndexer(inputCol="c").fit(df)
        other = DataFrame.from_table(ColumnTable.from_lists({"c": ["z"]}))
        with pytest.raises(ValueError):
            model.transform(other)

    def test_vector_assembler_skip(self, df):
        assembler = VectorAssembler(
            inputCols=["age", "fare"], outputCol="features"
        ).setHandleInvalid("skip")
        out = assembler.transform(df)
        assert out.count() == 2  # the null-age row was skipped
        assert out.feature_matrix().shape == (2, 2)

    def test_vector_assembler_error(self, df):
        assembler = VectorAssembler(inputCols=["age"], outputCol="features")
        with pytest.raises(ValueError):
            assembler.transform(df)


# The documented preprocessor example, verbatim from the reference's
# docs/model_builder.md (the compatibility contract for user code).
DOCUMENTED_PREPROCESSOR = r"""
from pyspark.ml import Pipeline
from pyspark.sql.functions import (
    mean, col, split,
    regexp_extract, when, lit)

from pyspark.ml.feature import (
    VectorAssembler,
    StringIndexer
)

TRAINING_DF_INDEX = 0
TESTING_DF_INDEX = 1

training_df = training_df.withColumnRenamed('Survived', 'label')
testing_df = testing_df.withColumn('label', lit(0))
datasets_list = [training_df, testing_df]

for index, dataset in enumerate(datasets_list):
    dataset = dataset.withColumn(
        "Initial",
        regexp_extract(col("Name"), "([A-Za-z]+)\.", 1))
    datasets_list[index] = dataset

misspelled_initials = [
    'Mlle', 'Mme', 'Ms', 'Dr',
    'Major', 'Lady', 'Countess',
    'Jonkheer', 'Col', 'Rev',
    'Capt', 'Sir', 'Don'
]
correct_initials = [
    'Miss', 'Miss', 'Miss', 'Mr',
    'Mr', 'Mrs', 'Mrs',
    'Other', 'Other', 'Other',
    'Mr', 'Mr', 'Mr'
]
for index, dataset in enumerate(datasets_list):
    dataset = dataset.replace(misspelled_initials, correct_initials)
    datasets_list[index] = dataset

initials_age = {"Miss": 22,
                "Other": 46,
                "Master": 5,
                "Mr": 33,
                "Mrs": 36}
for index, dataset in enumerate(datasets_list):
    for initial, initial_age in initials_age.items():
        dataset = dataset.withColumn(
            "Age",
            when((dataset["Initial"] == initial) &
                 (dataset["Age"].isNull()), initial_age).otherwise(
                    dataset["Age"]))
        datasets_list[index] = dataset

for index, dataset in enumerate(datasets_list):
    dataset = dataset.na.fill({"Embarked": 'S'})
    datasets_list[index] = dataset

for index, dataset in enumerate(datasets_list):
    dataset = dataset.withColumn("Family_Size", col('SibSp')+col('Parch'))
    dataset = dataset.withColumn('Alone', lit(0))
    dataset = dataset.withColumn(
        "Alone",
        when(dataset["Family_Size"] == 0, 1).otherwise(dataset["Alone"]))
    datasets_list[index] = dataset

text_fields = ["Sex", "Embarked", "Initial"]
for column in text_fields:
    for index, dataset in enumerate(datasets_list):
        dataset = StringIndexer(
            inputCol=column, outputCol=column+"_index").\
                fit(dataset).\
                transform(dataset)
        datasets_list[index] = dataset

non_required_columns = ["Name", "Embarked", "Sex", "Initial"]
for index, dataset in enumerate(datasets_list):
    dataset = dataset.drop(*non_required_columns)
    datasets_list[index] = dataset

training_df = datasets_list[TRAINING_DF_INDEX]
testing_df = datasets_list[TESTING_DF_INDEX]

assembler = VectorAssembler(
    inputCols=training_df.columns[:],
    outputCol="features")
assembler.setHandleInvalid('skip')

features_training = assembler.transform(training_df)
(features_training, features_evaluation) =\
    features_training.randomSplit([0.8, 0.2], seed=33)
features_testing = assembler.transform(testing_df)
"""


class TestDocumentedPreprocessor:
    def test_runs_verbatim(self, store, titanic_csv):
        write_ingest_metadata(store, "titanic", titanic_csv)
        ingest_csv(store, "titanic", titanic_csv)
        convert_field_types(
            store,
            "titanic",
            {
                f: "number"
                for f in ("PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare")
            },
        )
        table = ColumnTable.from_store(store, "titanic")
        training_df = DataFrame.from_table(table)
        testing_df = DataFrame.from_table(table).drop("Survived")

        out = run_preprocessor(DOCUMENTED_PREPROCESSOR, training_df, testing_df)
        features_training = out["features_training"]
        features_testing = out["features_testing"]
        features_evaluation = out["features_evaluation"]

        assert "features" in features_training.columns
        assert "label" in features_training.columns
        n_train = features_training.count()
        n_eval = features_evaluation.count()
        assert n_train + n_eval == 8  # no rows lost: Age was imputed
        assert features_testing.count() == 8
        # assembled width: label,PassengerId,Pclass,Age,SibSp,Parch,Fare,
        # Family_Size,Alone,Sex_index,Embarked_index,Initial_index
        assert features_training.feature_matrix().shape[1] == 12
        # label round-trips for training
        labels = features_training.label_vector()
        assert set(labels) <= {0, 1}


class TestReviewRegressions:
    def test_ne_null_is_false(self, df):
        out = df.filter(df["name"] != "Braund, Mr. Owen")
        assert list(out._column("name")) == ["Cumings, Mrs. John"]

    def test_na_fill_scalar_type_matching(self, df):
        filled = df.na.fill("S")  # string fill skips numeric columns
        assert np.isnan(filled._column("age")[1])
        assert filled._column("name")[2] == "S"
        filled = df.na.fill(0)  # numeric fill skips string columns
        assert filled._column("age")[1] == 0
        assert filled._column("name")[2] is None

    def test_when_without_otherwise_numeric_nan(self, df):
        out = df.withColumn("flag", when(df["fare"] > 7.5, 1))
        flag = out._column("flag")
        assert flag.dtype == np.float64
        assert np.isnan(flag[0]) and flag[1] == 1
        bumped = out.withColumn("flag2", col("flag") + 1)
        assert bumped._column("flag2")[1] == 2

    def test_label_vector_rejects_nan(self, df):
        frame = df.withColumnRenamed("age", "label")
        with pytest.raises(ValueError):
            frame.label_vector()

    def test_split_equal_lengths_stays_1d(self, df):
        from learningorchestra_tpu.frame.expressions import split

        frame = DataFrame.from_table(
            ColumnTable.from_lists({"s": ["a b", "c d", "e f"]})
        )
        out = frame.withColumn("parts", split(col("s"), " "))
        parts = out._column("parts")
        assert parts.ndim == 1 and parts[0] == ["a", "b"]

    def test_reflected_div_and_neg(self, df):
        out = df.withColumn("inv", 1 / col("fare")).withColumn(
            "neg", -col("fare")
        )
        np.testing.assert_allclose(out._column("inv")[0], 1 / 7.25)
        np.testing.assert_allclose(out._column("neg")[0], -7.25)
