"""Crash-resume contract (docs/robustness.md): progress artifacts,
resume-aware recovery, partial results, and the fault-point wiring that
the chaos drills lean on.

The bit-identity claim is load-bearing: a resumed fit must produce the
SAME model as an uninterrupted one, so resume is a pure wall-clock
optimization with no accuracy asterisk. The tests here prove it at the
unit level (segment restore → identical params); the subprocess kill -9
drill in tests/test_chaos.py proves it end to end.
"""

import os
import shutil

import numpy as np
import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.store import METADATA_ID, ROW_ID
from learningorchestra_tpu.ml.progress import ProgressSink, bind_sink, device_restore
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.sched.journal import JobJournal
from learningorchestra_tpu.telemetry import metrics as metrics_mod
from learningorchestra_tpu.testing import faults
from tests.test_frame import DOCUMENTED_PREPROCESSOR

NUMERIC_FIELDS = ("PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare")

META = {"training_fp": "a" * 16, "test_fp": "b" * 16, "dtype_policy": "f32", "mesh": "m"}


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def titanic_store(store, titanic_csv):
    for name in ("titanic_train", "titanic_test"):
        write_ingest_metadata(store, name, titanic_csv)
        ingest_csv(store, name, titanic_csv)
        convert_field_types(store, name, {f: "number" for f in NUMERIC_FIELDS})
    return store


def _counter_value(name: str) -> float:
    registry = metrics_mod.global_registry()
    counter = registry.counter(name, "probe")
    return counter.value()


class TestProgressSink:
    def test_round_trip(self, tmp_path):
        sink = ProgressSink(str(tmp_path / "m.progress"), dict(META))
        arrays = [np.arange(6.0).reshape(2, 3), np.array([1, 2], np.int32)]
        sink.save("logistic", 2, arrays, {"iters": 25, "history": [0.5]})
        restored = sink.load("logistic")
        assert restored is not None
        segment, back, scalars = restored
        assert segment == 2
        assert scalars == {"iters": 25, "history": [0.5]}
        np.testing.assert_array_equal(back[0], arrays[0])
        np.testing.assert_array_equal(back[1], arrays[1])
        assert back[1].dtype == np.int32

    def test_every_grid_skips_off_grid_segments(self, tmp_path):
        fired = []
        sink = ProgressSink(
            str(tmp_path / "m.progress"),
            dict(META),
            every=2,
            on_segment=fired.append,
        )
        sink.save("logistic", 1, [np.zeros(2)], {})
        assert not os.path.exists(sink.path)
        assert fired == []
        sink.save("logistic", 2, [np.zeros(2)], {})
        assert os.path.exists(sink.path)
        assert fired == [2]

    def test_kind_mismatch_deletes(self, tmp_path):
        sink = ProgressSink(str(tmp_path / "m.progress"), dict(META))
        sink.save("logistic", 1, [np.zeros(2)], {})
        assert sink.load("gbt") is None
        assert not os.path.exists(sink.path)

    def test_stale_meta_deletes(self, tmp_path):
        path = str(tmp_path / "m.progress")
        ProgressSink(path, dict(META)).save("logistic", 1, [np.zeros(2)], {})
        stale = dict(META, training_fp="c" * 16)
        assert ProgressSink(path, stale).load("logistic") is None
        assert not os.path.exists(path)

    def test_corrupt_artifact_deletes(self, tmp_path):
        path = str(tmp_path / "m.progress")
        with open(path, "wb") as handle:
            handle.write(b"not a zip archive")
        assert ProgressSink(path, dict(META)).load("logistic") is None
        assert not os.path.exists(path)

    def test_discard_and_missing_file(self, tmp_path):
        sink = ProgressSink(str(tmp_path / "m.progress"), dict(META))
        assert sink.load("logistic") is None  # nothing saved yet
        sink.save("logistic", 1, [np.zeros(2)], {})
        sink.discard()
        assert not os.path.exists(sink.path)
        sink.discard()  # idempotent


class TestCollectionFingerprint:
    """The validation key must survive a process restart — collection
    revs reseed from a random base per boot, which is why the key uses
    content fingerprints instead (the restarted process is the one that
    needs a pre-crash artifact to validate)."""

    def test_stable_across_wal_reload(self, tmp_path):
        from learningorchestra_tpu.core.store import InMemoryStore
        from learningorchestra_tpu.ml.progress import collection_fingerprint

        data_dir = str(tmp_path / "lo_data")
        first = InMemoryStore(data_dir=data_dir)
        first.insert_many(
            "drill", [{"_id": i, "f1": i * 0.5} for i in range(1, 6)]
        )
        before = collection_fingerprint(first, "drill")

        second = InMemoryStore(data_dir=data_dir)  # same WAL, new boot
        assert second.collection_rev("drill") != first.collection_rev(
            "drill"
        ), "revs ARE boot-scoped; if this ever holds, revs would suffice"
        assert collection_fingerprint(second, "drill") == before

    def test_mutation_changes_fingerprint(self, store):
        from learningorchestra_tpu.ml.progress import collection_fingerprint

        store.insert_many(
            "drill", [{"_id": i, "f1": i * 0.5} for i in range(1, 6)]
        )
        before = collection_fingerprint(store, "drill")
        store.update_one("drill", {"_id": 3}, {"f1": -1.0})
        assert collection_fingerprint(store, "drill") != before

    def test_save_is_best_effort(self, tmp_path):
        # an unwritable progress dir costs resume granularity, not the fit
        sink = ProgressSink(
            str(tmp_path / "missing_dir" / "m.progress"), dict(META)
        )
        sink.save("logistic", 1, [np.zeros(2)], {})  # must not raise
        assert sink.load("logistic") is None


class TestDeviceRestore:
    def _template(self):
        import jax.numpy as jnp

        return (jnp.zeros((2, 3), jnp.float32), jnp.zeros((3,), jnp.float32))

    def test_restores_matching_arrays(self):
        template = self._template()
        hosts = [
            np.arange(6, dtype=np.float32).reshape(2, 3),
            np.arange(3, dtype=np.float32),
        ]
        restored = device_restore(template, hosts)
        assert restored is not None
        np.testing.assert_array_equal(np.asarray(restored[0]), hosts[0])
        np.testing.assert_array_equal(np.asarray(restored[1]), hosts[1])

    def test_leaf_count_mismatch(self):
        assert device_restore(self._template(), [np.zeros((2, 3))]) is None

    def test_shape_mismatch(self):
        hosts = [np.zeros((2, 4), np.float32), np.zeros((3,), np.float32)]
        assert device_restore(self._template(), hosts) is None

    def test_dtype_mismatch(self):
        hosts = [np.zeros((2, 3), np.float64), np.zeros((3,), np.float32)]
        assert device_restore(self._template(), hosts) is None


class TestLogisticResumeBitIdentity:
    def test_resumed_fit_matches_uninterrupted(self, tmp_path):
        """Kill-at-segment-2 simulation: copy the segment-2 artifact
        aside mid-run, restore it, refit — the resumed fit must skip
        two segments and land on bit-identical params."""
        import jax

        from learningorchestra_tpu.ml.logistic import LogisticRegression

        rng = np.random.default_rng(11)
        X = rng.random((64, 5)).astype(np.float64)
        y = (X[:, 0] > 0.5).astype(np.int64)
        # tol tiny-but-positive keeps the 25-iteration convergence-check
        # segmentation (max_iter=100 → up to 4 segments); the fit may
        # still plateau early once fully converged (zero deltas pass any
        # positive tol), so the assertions below count segments rather
        # than assume all four run
        tol = 1e-12
        control = LogisticRegression(max_iter=100, tol=tol).fit(X, y)

        path = str(tmp_path / "m.progress")
        aside = str(tmp_path / "segment2.progress")
        segments: list[int] = []

        def record(segment: int) -> None:
            segments.append(segment)
            if segment == 2:
                shutil.copyfile(path, aside)

        first = ProgressSink(path, dict(META), on_segment=record)
        with bind_sink(first):
            uninterrupted = LogisticRegression(max_iter=100, tol=tol).fit(X, y)
        assert os.path.exists(aside), "fit never reached segment 2"
        total_run = segments[-1]
        assert total_run >= 2

        # the "restarted process": same meta, the mid-fit artifact back
        # in place
        shutil.copyfile(aside, path)
        skipped_before = _counter_value("lo_build_segments_skipped_total")
        saved_before = _counter_value("lo_build_segments_saved_total")
        with bind_sink(ProgressSink(path, dict(META))):
            resumed = LogisticRegression(max_iter=100, tol=tol).fit(X, y)
        assert _counter_value("lo_build_segments_skipped_total") - skipped_before == 2
        # the resumed run re-runs EXACTLY the segments the control ran
        # past the restore point — stopping where the control stopped,
        # even when that is "immediately" (plateau checked at loop top)
        assert (
            _counter_value("lo_build_segments_saved_total") - saved_before
            == total_run - 2
        )

        for fitted in (uninterrupted, resumed):
            for got, want in zip(
                jax.tree.leaves(fitted.params), jax.tree.leaves(control.params)
            ):
                np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_stale_artifact_restarts_clean(self, tmp_path):
        """A rev-mismatched artifact must be deleted and the fit rerun
        from scratch — never resumed into a silently-wrong model."""
        import jax

        from learningorchestra_tpu.ml.logistic import LogisticRegression

        rng = np.random.default_rng(12)
        X = rng.random((48, 4)).astype(np.float64)
        y = (X[:, 1] > 0.5).astype(np.int64)
        control = LogisticRegression(max_iter=50, tol=1e-12).fit(X, y)

        path = str(tmp_path / "m.progress")
        with bind_sink(ProgressSink(path, dict(META))):
            LogisticRegression(max_iter=50, tol=1e-12).fit(X, y)
        assert os.path.exists(path)

        stale = dict(META, training_fp="c" * 16)
        skipped_before = _counter_value("lo_build_segments_skipped_total")
        with bind_sink(ProgressSink(path, stale)):
            refit = LogisticRegression(max_iter=50, tol=1e-12).fit(X, y)
        assert _counter_value("lo_build_segments_skipped_total") == skipped_before
        for got, want in zip(
            jax.tree.leaves(refit.params), jax.tree.leaves(control.params)
        ):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestJournalProgress:
    def test_progress_folds_without_touching_state(self, store):
        journal = JobJournal(store)
        journal.append("j1", "submitted", op="build_model", payload={"a": 1})
        journal.append("j1", "started")
        journal.append("j1", "progress", classificator="lr", status="finished")
        journal.append("j1", "progress", classificator="dt", kind="segment", segment=3)
        history = journal.replay()["j1"]
        assert history.started and not history.terminal
        assert len(history.progress) == 2
        assert history.progress[0]["classificator"] == "lr"
        assert history.progress[1]["segment"] == 3

    def test_terminal_after_finish(self, store):
        journal = JobJournal(store)
        journal.append("j1", "submitted", op="build_model", payload={})
        journal.append("j1", "started")
        journal.append("j1", "progress", classificator="lr", status="finished")
        journal.append("j1", "finished")
        assert journal.replay()["j1"].terminal

    def test_append_fault_loses_audit_line_not_job(self, store):
        # chaos point sched.journal.append: an injected error must be
        # swallowed exactly like a real store hiccup
        journal = JobJournal(store)
        faults.install("sched.journal.append", "error@1")
        journal.append("j1", "submitted", op="build_model", payload={})
        journal.append("j1", "started")
        history = journal.replay().get("j1")
        # the submitted line was lost; the started line synthesized a
        # history so recovery can still terminate it
        assert history is not None and history.started


class _FakeJobs:
    def __init__(self):
        self.submissions = []
        self.journal = None

    def submit(self, name, fn, *args, **kwargs):
        self.submissions.append((name, fn, args, kwargs))


class TestRecoveryResume:
    @pytest.fixture(autouse=True)
    def _registries(self):
        from learningorchestra_tpu.sched import recovery

        replay = dict(recovery._REPLAY_REGISTRY)
        resume = dict(recovery._RESUME_REGISTRY)
        yield
        recovery._REPLAY_REGISTRY.clear()
        recovery._REPLAY_REGISTRY.update(replay)
        recovery._RESUME_REGISTRY.clear()
        recovery._RESUME_REGISTRY.update(resume)

    def _orphan_journal(self, store, op="stub_op", collection="c1"):
        journal = JobJournal(store)
        journal.append(
            "j1", "submitted", op=op, payload={"x": 1}, collection=collection
        )
        journal.append("j1", "started")
        journal.append("j1", "progress", classificator="lr", status="finished")
        journal.append("j1", "progress", classificator="dt", kind="segment", segment=2)
        return journal

    def test_orphaned_resumable_job_requeues_with_progress(self, store):
        from learningorchestra_tpu.sched import recovery

        def handler(store, payload, progress):
            raise AssertionError("recovery must enqueue, not run inline")

        recovery.register_resumable("stub_op", handler)
        journal = self._orphan_journal(store)
        jobs = _FakeJobs()
        resumed_before = _counter_value("lo_sched_resumed_total")
        outcome = recovery.recover_jobs(store, jobs, journal)
        assert outcome == {"requeued": ["j1"], "orphaned": []}
        assert _counter_value("lo_sched_resumed_total") - resumed_before == 1
        (name, fn, args, kwargs) = jobs.submissions[0]
        assert name == "j1" and fn is handler
        assert args[1] == {"x": 1}
        progress = args[2]
        assert [e.get("classificator") for e in progress] == ["lr", "dt"]
        assert kwargs["replay"] == ("stub_op", {"x": 1})
        # still RUNNING as far as the journal knows: no terminal event
        assert not journal.replay()["j1"].terminal

    def test_resume_disabled_orphans_instead(self, store, monkeypatch):
        from learningorchestra_tpu.sched import recovery

        monkeypatch.setenv("LO_RESUME", "0")
        recovery.register_resumable(
            "stub_op", lambda store, payload, progress: None
        )
        store.insert_one("c1", {ROW_ID: METADATA_ID, "finished": False})
        journal = self._orphan_journal(store)
        jobs = _FakeJobs()
        outcome = recovery.recover_jobs(store, jobs, journal)
        assert outcome == {"requeued": [], "orphaned": ["j1"]}
        assert jobs.submissions == []
        history = journal.replay()["j1"]
        assert history.terminal and history.last_error == recovery.ORPHAN_ERROR
        metadata = store.find_one("c1", {ROW_ID: METADATA_ID})
        assert metadata["finished"] is True
        assert metadata["error"] == recovery.ORPHAN_ERROR

    def test_non_resumable_started_op_orphans(self, store):
        from learningorchestra_tpu.sched import recovery

        journal = self._orphan_journal(store, op="no_such_op")
        jobs = _FakeJobs()
        outcome = recovery.recover_jobs(store, jobs, journal)
        assert outcome == {"requeued": [], "orphaned": ["j1"]}

    def test_build_model_registered_both_ways(self):
        from learningorchestra_tpu.sched import recovery

        assert "build_model" in recovery._REPLAY_REGISTRY
        assert "build_model" in recovery._RESUME_REGISTRY


class _FakeHandle:
    def __init__(self):
        self.detail = {}
        self.events = []

    def annotate(self, **detail):
        self.detail.update(detail)

    def progress(self, **fields):
        self.events.append(fields)


@pytest.fixture()
def fake_handle(monkeypatch):
    handle = _FakeHandle()
    monkeypatch.setattr(
        "learningorchestra_tpu.core.jobs.current_job_handle", lambda: handle
    )
    return handle


def _build(store, classifiers, **kwargs):
    from learningorchestra_tpu.ml.builder import build_model

    return build_model(
        store,
        "titanic_train",
        "titanic_test",
        DOCUMENTED_PREPROCESSOR,
        classifiers,
        **kwargs,
    )


def _fail_member(monkeypatch, *names):
    from learningorchestra_tpu.ml import builder

    real = builder.train_one

    def failing(store, name, *args, **kwargs):
        if name in names:
            raise RuntimeError(f"{name} exploded")
        return real(store, name, *args, **kwargs)

    monkeypatch.setattr(builder, "train_one", failing)


class TestPartialResults:
    def test_one_failure_returns_survivors(
        self, titanic_store, monkeypatch, fake_handle
    ):
        _fail_member(monkeypatch, "nb")
        results = _build(titanic_store, ["lr", "nb"])
        assert [r["classificator"] for r in results] == ["lr"]
        assert fake_handle.detail["result"] == "finished_partial"
        statuses = fake_handle.detail["classifiers"]
        assert statuses["lr"] == {"status": "finished"}
        assert statuses["nb"]["status"] == "failed"
        assert "nb exploded" in statuses["nb"]["error"]
        # the journal trail the resumed run folds: lr durably finished,
        # nb permanently failed
        assert {"classificator": "lr", "status": "finished"} in fake_handle.events
        failed = [e for e in fake_handle.events if e.get("status") == "failed"]
        assert failed and failed[0]["classificator"] == "nb"

    def test_single_member_failure_reraises_verbatim(
        self, titanic_store, monkeypatch, fake_handle
    ):
        _fail_member(monkeypatch, "nb")
        with pytest.raises(RuntimeError, match="nb exploded"):
            _build(titanic_store, ["nb"])
        assert "result" not in fake_handle.detail

    def test_all_failed_multi_aggregates(
        self, titanic_store, monkeypatch, fake_handle
    ):
        _fail_member(monkeypatch, "lr", "nb")
        with pytest.raises(RuntimeError, match="all classifiers failed"):
            _build(titanic_store, ["lr", "nb"])

    def test_fault_injected_member_yields_partial(
        self, titanic_store, fake_handle
    ):
        # the compute-plane chaos point: one classifier's fit phase
        # errors, the build still FINISHES with the survivor's outputs
        faults.install(
            "builder.phase", "error@1", where={"phase": "fit", "classificator": "nb"}
        )
        results = _build(titanic_store, ["lr", "nb"])
        assert [r["classificator"] for r in results] == ["lr"]
        assert fake_handle.detail["result"] == "finished_partial"
        assert fake_handle.detail["classifiers"]["nb"]["status"] == "failed"


class TestResumeSkips:
    def test_finished_member_not_refit(
        self, titanic_store, monkeypatch, fake_handle
    ):
        results = _build(titanic_store, ["lr"])
        stored = titanic_store.find_one(
            "titanic_test_prediction_lr", {ROW_ID: 0}
        )
        assert stored is not None
        fake_handle.events.clear()

        from learningorchestra_tpu.ml import builder

        def must_not_run(*args, **kwargs):
            raise AssertionError("finished member must not refit")

        monkeypatch.setattr(builder, "train_one", must_not_run)
        resumed = _build(
            titanic_store,
            ["lr"],
            resume=[{"classificator": "lr", "status": "finished"}],
        )
        assert resumed == [stored]
        assert fake_handle.events == []  # no re-journaled completion
        assert results[0]["accuracy"] == stored["accuracy"]

    def test_finished_member_with_dropped_outputs_rebuilds(
        self, titanic_store, fake_handle
    ):
        # journaled finished but the collection is gone: rebuild, don't
        # return nothing
        resumed = _build(
            titanic_store,
            ["lr"],
            resume=[{"classificator": "lr", "status": "finished"}],
        )
        assert resumed[0]["classificator"] == "lr"
        assert {"classificator": "lr", "status": "finished"} in fake_handle.events

    def test_failed_member_stays_failed_without_rerun(
        self, titanic_store, monkeypatch, fake_handle
    ):
        from learningorchestra_tpu.ml import builder

        real = builder.train_one

        def guarded(store, name, *args, **kwargs):
            assert name != "nb", "failed member must not re-run"
            return real(store, name, *args, **kwargs)

        monkeypatch.setattr(builder, "train_one", guarded)
        results = _build(
            titanic_store,
            ["lr", "nb"],
            resume=[
                {
                    "classificator": "nb",
                    "status": "failed",
                    "error": "boom before restart",
                }
            ],
        )
        assert [r["classificator"] for r in results] == ["lr"]
        statuses = fake_handle.detail["classifiers"]
        assert statuses["nb"] == {
            "status": "failed",
            "error": "boom before restart",
        }
        # already journaled by the pre-crash run: no duplicate event
        assert not any(
            e.get("status") == "failed" for e in fake_handle.events
        )

    def test_later_events_win_in_fold(self):
        from learningorchestra_tpu.ml.builder import _fold_resume

        done = _fold_resume(
            [
                {"classificator": "lr", "status": "failed", "error": "x"},
                {"classificator": "dt", "kind": "segment", "segment": 2},
                {"classificator": "lr", "status": "finished"},
            ]
        )
        assert done == {"lr": {"status": "finished", "error": None}}


class TestResumeKnobs:
    def test_defaults(self, monkeypatch):
        from learningorchestra_tpu.sched import config

        monkeypatch.delenv("LO_RESUME", raising=False)
        monkeypatch.delenv("LO_RESUME_EVERY_SEGMENTS", raising=False)
        assert config.resume_enabled() is True
        assert config.resume_every_segments() == 1

    def test_disable(self, monkeypatch):
        from learningorchestra_tpu.sched import config

        monkeypatch.setenv("LO_RESUME", "0")
        assert config.resume_enabled() is False

    @pytest.mark.parametrize("value", ["yes", "2", "true"])
    def test_enabled_rejects_non_binary(self, monkeypatch, value):
        from learningorchestra_tpu.sched import config

        monkeypatch.setenv("LO_RESUME", value)
        with pytest.raises(ValueError):
            config.resume_enabled()

    @pytest.mark.parametrize("value", ["0", "1.5", "-2", "abc"])
    def test_every_segments_rejects(self, monkeypatch, value):
        from learningorchestra_tpu.sched import config

        monkeypatch.setenv("LO_RESUME_EVERY_SEGMENTS", value)
        with pytest.raises(ValueError):
            config.resume_every_segments()

    def test_every_segments_accepts_integral(self, monkeypatch):
        from learningorchestra_tpu.sched import config

        monkeypatch.setenv("LO_RESUME_EVERY_SEGMENTS", "3")
        assert config.resume_every_segments() == 3
