"""Scheduler subsystem: admission control, priorities, retries,
deadlines, cancellation, journal recovery — and the end-to-end
guarantees the services inherit (device-class serialization, 429
backpressure, crash recovery leaving no job with ``finished: false``).
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from learningorchestra_tpu.core.jobs import (
    CANCELLED,
    FAILED,
    FINISHED,
    JobManager,
)
from learningorchestra_tpu.core.store import (
    METADATA_ID,
    ROW_ID,
    InMemoryStore,
)
from learningorchestra_tpu.sched import (
    DEVICE_CLASS,
    HOST_CLASS,
    JobJournal,
    QueueFullError,
    Scheduler,
    TransientJobError,
    backoff_delay,
    check_cancelled,
    recover_jobs,
)
from learningorchestra_tpu.sched import config as sched_config
from learningorchestra_tpu.sched.journal import JOURNAL_COLLECTION
from learningorchestra_tpu.sched.policy import is_transient


def body(response):
    return json.loads(response.get_data())


def make_manager(**scheduler_kwargs) -> JobManager:
    return JobManager(scheduler=Scheduler(**scheduler_kwargs))


# --------------------------------------------------------------------
# Admission control / backpressure
# --------------------------------------------------------------------


class TestAdmissionControl:
    def test_flood_past_queue_cap_is_deterministic_429(self):
        manager = make_manager(host_width=1, queue_cap=2)
        gate = threading.Event()
        manager.submit("hold", gate.wait)
        # give the single worker time to occupy itself with "hold"
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("q1", lambda: None)
        manager.submit("q2", lambda: None)
        # cap=2 and 2 queued: every further submit MUST refuse, with a
        # positive Retry-After — deterministically, not racily
        for attempt in range(5):
            with pytest.raises(QueueFullError) as info:
                manager.submit(f"overflow{attempt}", lambda: None)
            assert info.value.retry_after_s >= 1
            assert info.value.job_class == HOST_CLASS
        # rejected submissions left no tracked record behind
        names = {job["name"] for job in manager.all_jobs()}
        assert names == {"hold", "q1", "q2"}
        gate.set()
        assert manager.wait("q2", timeout=10).state == FINISHED

    def test_rejected_name_is_resubmittable(self):
        manager = make_manager(host_width=1, queue_cap=1)
        gate = threading.Event()
        manager.submit("hold", gate.wait)
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("fill", lambda: None)
        with pytest.raises(QueueFullError):
            manager.submit("again", lambda: None)
        gate.set()
        manager.wait("fill", timeout=10)
        # the 429'd name was fully unregistered: resubmit works
        manager.submit("again", lambda: None)
        assert manager.wait("again", timeout=10).state == FINISHED

    def test_rest_flood_429_with_retry_after(self, tmp_path):
        from learningorchestra_tpu.services import database_api

        store = InMemoryStore()
        jobs = make_manager(host_width=1, queue_cap=1)
        client = database_api.create_app(store, jobs).test_client()
        gate = threading.Event()
        jobs.submit("hold", gate.wait)
        deadline = time.time() + 5
        while jobs.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        csv = tmp_path / "flood.csv"
        csv.write_text("a\n1\n")
        first = client.post(
            "/files", json={"filename": "flood0", "url": str(csv)}
        )
        assert first.status_code == 201
        rejected = client.post(
            "/files", json={"filename": "flood1", "url": str(csv)}
        )
        assert rejected.status_code == 429
        assert int(rejected.headers["Retry-After"]) >= 1
        assert body(rejected)["result"] == "queue_full"
        # the name claim was released with the rejection: after the
        # queue drains, the same request succeeds
        gate.set()
        jobs.wait("ingest:flood0", timeout=30)
        retried = client.post(
            "/files", json={"filename": "flood1", "url": str(csv)}
        )
        assert retried.status_code == 201
        jobs.wait("ingest:flood1", timeout=30)

    def test_priority_orders_queue(self):
        manager = make_manager(host_width=1, queue_cap=16)
        gate = threading.Event()
        order: list[str] = []
        manager.submit("hold", gate.wait)
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("low", order.append, "low", priority=0)
        manager.submit("high", order.append, "high", priority=10)
        manager.submit("mid", order.append, "mid", priority=5)
        gate.set()
        for name in ("low", "high", "mid"):
            manager.wait(name, timeout=10)
        assert order == ["high", "mid", "low"]


# --------------------------------------------------------------------
# Retry policy
# --------------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_jitter_sequence_is_golden(self):
        # deterministic seeded jitter: the exact sequence is part of
        # the contract (journal replay re-derives the same delays)
        observed = [
            backoff_delay("build:x", n, base_s=0.5, cap_s=60.0, seed=0)
            for n in (1, 2, 3, 4, 5)
        ]
        assert observed == pytest.approx(
            [
                0.4633463628,
                1.094119149,
                2.4990444475,
                3.7282882016,
                6.9849966953,
            ]
        )
        # the cap bounds the exponential term before jitter
        capped = [
            backoff_delay("build:x", n, base_s=0.5, cap_s=2.0, seed=7)
            for n in (1, 2, 3)
        ]
        assert capped == pytest.approx(
            [0.4438675434, 0.9813357367, 1.7817060331]
        )
        # distinct jobs decorrelate; same job+attempt reproduces
        assert backoff_delay("a", 1, 0.5, 60.0, 0) != backoff_delay(
            "b", 1, 0.5, 60.0, 0
        )
        assert backoff_delay("a", 1, 0.5, 60.0, 0) == backoff_delay(
            "a", 1, 0.5, 60.0, 0
        )

    def test_transient_classification(self):
        assert is_transient(TransientJobError("hiccup"))
        assert not is_transient(ValueError("bad input"))

        class SpmdTimeoutError(RuntimeError):  # name-matched, no jax
            pass

        assert is_transient(SpmdTimeoutError("watchdog"))

    def test_transient_failure_retries_then_finishes(self, monkeypatch):
        monkeypatch.setenv("LO_SCHED_BACKOFF_S", "0.01")
        manager = make_manager()
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise TransientJobError("store failing over")

        manager.submit("flaky", flaky)
        record = manager.wait("flaky", timeout=30)
        assert record.state == FINISHED
        assert record.attempts == 3
        assert len(attempts) == 3

    def test_budget_exhausted_is_terminal_and_flips_finished(
        self, monkeypatch
    ):
        monkeypatch.setenv("LO_SCHED_BACKOFF_S", "0.01")
        monkeypatch.setenv("LO_SCHED_RETRIES", "2")
        store = InMemoryStore()
        store.insert_one(
            "ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False}
        )
        manager = make_manager()

        def always_failing():
            raise TransientJobError("never recovers")

        manager.submit(
            "doomed", always_failing, store=store, collection="ds"
        )
        record = manager.wait("doomed", timeout=30)
        assert record.state == FAILED
        assert record.attempts == 2
        metadata = store.find_one("ds", {ROW_ID: METADATA_ID})
        assert metadata["finished"] is True
        assert "never recovers" in metadata["error"]

    def test_store_failure_during_finalize_still_wakes_waiters(self):
        # the cardinal sin would be a hung done event: a store that is
        # down exactly when a job fails must not stop finalization
        class ExplodingStore(InMemoryStore):
            def update_one(self, collection, query, new_values):
                raise ConnectionError("store mid-failover")

        store = ExplodingStore()
        store.insert_one(
            "ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False}
        )
        manager = make_manager()

        def bad():
            raise ValueError("boom")

        manager.submit("doomed", bad, store=store, collection="ds")
        record = manager.wait("doomed", timeout=10)  # must NOT hang
        assert record.state == FAILED
        assert "boom" in record.error

    def test_terminal_failure_does_not_retry(self):
        manager = make_manager()
        calls = []

        def bad_input():
            calls.append(1)
            raise ValueError("not transient")

        manager.submit("bad", bad_input)
        record = manager.wait("bad", timeout=10)
        assert record.state == FAILED
        assert calls == [1]


# --------------------------------------------------------------------
# Deadlines and cancellation
# --------------------------------------------------------------------


class TestCancellation:
    def test_cancel_running_job_cooperatively(self):
        manager = make_manager()
        started = threading.Event()

        def spin():
            started.set()
            while True:
                check_cancelled()
                time.sleep(0.005)

        manager.submit("spin", spin)
        assert started.wait(10)
        assert manager.cancel("spin") == "cancelling"
        record = manager.wait("spin", timeout=10)
        assert record.state == CANCELLED
        assert manager.cancel("spin") == "terminal"
        assert manager.cancel("missing") == "unknown"

    def test_cancel_queued_job_never_runs(self):
        manager = make_manager(host_width=1, queue_cap=8)
        gate = threading.Event()
        ran = []
        manager.submit("hold", gate.wait)
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("queued", ran.append, 1)
        assert manager.cancel("queued") == "cancelling"
        gate.set()
        record = manager.wait("queued", timeout=10)
        assert record.state == CANCELLED
        assert ran == []

    def test_cancelled_job_terminates_pollers(self):
        store = InMemoryStore()
        store.insert_one(
            "ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False}
        )
        manager = make_manager()
        started = threading.Event()

        def spin():
            started.set()
            while True:
                check_cancelled()
                time.sleep(0.005)

        manager.submit("spin", spin, store=store, collection="ds")
        assert started.wait(10)
        manager.cancel("spin")
        manager.wait("spin", timeout=10)
        metadata = store.find_one("ds", {ROW_ID: METADATA_ID})
        assert metadata["finished"] is True

    def test_deadline_fails_queued_job_without_running(self):
        manager = make_manager(host_width=1, queue_cap=8)
        gate = threading.Event()
        ran = []
        manager.submit("hold", gate.wait)
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("expiring", ran.append, 1, timeout=0.05)
        time.sleep(0.2)
        gate.set()
        record = manager.wait("expiring", timeout=10)
        assert record.state == FAILED
        assert "JobTimeoutError" in record.error
        assert ran == []

    def test_delete_route_cancels(self):
        from learningorchestra_tpu.services import database_api

        store = InMemoryStore()
        jobs = make_manager()
        client = database_api.create_app(store, jobs).test_client()
        started = threading.Event()

        def spin():
            started.set()
            while True:
                check_cancelled()
                time.sleep(0.005)

        jobs.submit("spin", spin)
        assert started.wait(10)
        assert client.delete("/jobs/spin").status_code == 202
        record = jobs.wait("spin", timeout=10)
        assert record.state == CANCELLED
        assert client.delete("/jobs/spin").status_code == 409
        assert client.delete("/jobs/missing").status_code == 404
        listing = body(client.get("/jobs"))["result"]
        (job,) = [j for j in listing if j["name"] == "spin"]
        assert job["state"] == "cancelled"
        assert job["job_class"] == HOST_CLASS


# --------------------------------------------------------------------
# Journal + recovery
# --------------------------------------------------------------------


class TestJournalRecovery:
    def test_journal_records_lifecycle(self):
        store = InMemoryStore()
        manager = make_manager(journal=JobJournal(store))
        manager.submit("ok", lambda: None)
        manager.wait("ok", timeout=10)
        events = [
            (doc["job"], doc["event"])
            for doc in store.find(JOURNAL_COLLECTION)
        ]
        assert events == [
            ("ok", "submitted"),
            ("ok", "started"),
            ("ok", "finished"),
        ]

    def test_ephemeral_sync_jobs_skip_the_journal(self):
        # run_sync with no replay op and no tracked collection: the
        # caller sees the outcome directly, recovery could only ever
        # mark it orphaned — journaling it is pure write amplification
        store = InMemoryStore()
        manager = make_manager(journal=JobJournal(store))
        manager.run_sync("ephemeral", lambda: None)
        assert list(store.find(JOURNAL_COLLECTION)) == []
        # a tracked sync job still journals (its pollers need recovery)
        store.insert_one(
            "ds", {ROW_ID: METADATA_ID, "filename": "ds", "finished": False}
        )
        manager.run_sync("tracked", lambda: None, store=store, collection="ds")
        events = [
            (doc["job"], doc["event"])
            for doc in store.find(JOURNAL_COLLECTION)
        ]
        assert ("tracked", "finished") in events
        assert all(job != "ephemeral" for job, _ in events)

    def test_replay_after_simulated_restart_leaves_no_hung_poller(
        self, tmp_path
    ):
        # The acceptance scenario: a "crashed" process left one job
        # RUNNING (orphan) and one admitted-but-never-started ingest.
        # After replay, NO collection may still read finished: false.
        store = InMemoryStore()
        journal = JobJournal(store)
        csv = tmp_path / "ok.csv"
        csv.write_text("a,b\n1,2\n3,4\n")
        for name in ("orphan_ds", "queued_ds"):
            store.insert_one(
                name,
                {ROW_ID: METADATA_ID, "filename": name, "finished": False},
            )
        journal.append(
            "build:orphan_ds",
            "submitted",
            job_class=DEVICE_CLASS,
            priority=0,
            collection="orphan_ds",
        )
        journal.append("build:orphan_ds", "started", attempt=1)
        journal.append(
            "ingest:queued_ds",
            "submitted",
            job_class=HOST_CLASS,
            priority=0,
            op="ingest",
            payload={"filename": "queued_ds", "url": str(csv)},
            collection="queued_ds",
        )
        # "restart": a fresh manager over the same store
        manager = make_manager(journal=JobJournal(store))
        outcome = recover_jobs(store, manager)
        assert outcome["orphaned"] == ["build:orphan_ds"]
        assert outcome["requeued"] == ["ingest:queued_ds"]
        orphan_meta = store.find_one("orphan_ds", {ROW_ID: METADATA_ID})
        assert orphan_meta["finished"] is True
        assert "orphaned" in orphan_meta["error"]
        record = manager.wait("ingest:queued_ds", timeout=30)
        assert record.state == FINISHED
        # recovery with live work is append-only (a crash mid-recovery
        # must never lose a job): the orphan got a terminal event, the
        # requeue a fresh submitted/started/finished tail
        events = [
            (doc["job"], doc["event"])
            for doc in store.find(JOURNAL_COLLECTION)
        ]
        assert ("ingest:queued_ds", "finished") in events
        assert ("build:orphan_ds", "orphaned") in events
        # the end state the reference can never reach: every dataset
        # metadata document terminated its pollers
        for name in ("orphan_ds", "queued_ds"):
            assert store.find_one(name, {ROW_ID: METADATA_ID})["finished"]
        # a SECOND restart finds everything terminal and no foreign
        # scopes → the journal compacts to nothing
        second = recover_jobs(
            store, make_manager(journal=JobJournal(store)), JobJournal(store)
        )
        assert second == {"requeued": [], "orphaned": []}
        assert list(store.find(JOURNAL_COLLECTION)) == []

    def test_scoped_recovery_leaves_other_scopes_alone(self):
        store = InMemoryStore()
        JobJournal(store, scope="database_api").append(
            "ingest:a", "submitted", op="ingest", payload={}
        )
        JobJournal(store, scope="model_builder").append(
            "build:b", "submitted", collection=None
        )
        manager = make_manager(
            journal=JobJournal(store, scope="model_builder")
        )
        outcome = recover_jobs(
            store, manager, JobJournal(store, scope="model_builder")
        )
        # build:b has no replay handler → terminal; ingest:a belongs to
        # database_api's scope and must be untouched
        assert outcome["orphaned"] == ["build:b"]
        assert outcome["requeued"] == []
        events = [
            (doc["job"], doc["event"], doc["scope"])
            for doc in store.find(JOURNAL_COLLECTION)
        ]
        assert ("ingest:a", "submitted", "database_api") in events
        assert ("build:b", "orphaned", "model_builder") in events

    def test_rejected_submission_is_terminal_in_journal(self):
        store = InMemoryStore()
        manager = make_manager(
            host_width=1, queue_cap=1, journal=JobJournal(store)
        )
        gate = threading.Event()
        manager.submit("hold", gate.wait)
        deadline = time.time() + 5
        while manager.get("hold").state == "pending":
            assert time.time() < deadline
            time.sleep(0.005)
        manager.submit("fill", lambda: None)
        with pytest.raises(QueueFullError):
            manager.submit("rejected", lambda: None)
        gate.set()
        manager.wait("fill", timeout=10)
        # a 429'd job must not be resurrected by the next restart
        fresh = make_manager(journal=JobJournal(store))
        outcome = recover_jobs(store, fresh, JobJournal(store))
        assert "rejected" not in outcome["requeued"]
        assert "rejected" not in outcome["orphaned"]


# --------------------------------------------------------------------
# End-to-end: device-class serialization over REST
# --------------------------------------------------------------------


class TestDeviceClassEndToEnd:
    @pytest.fixture()
    def titanic_like(self):
        store = InMemoryStore()
        for name in ("train_ds", "test_ds"):
            store.insert_one(
                name,
                {ROW_ID: METADATA_ID, "filename": name, "finished": True},
            )
        return store

    def test_concurrent_builds_never_overlap_on_the_mesh(
        self, titanic_like
    ):
        from learningorchestra_tpu.services import model_builder

        jobs = make_manager(device_width=1, queue_cap=2)
        in_flight = []
        max_in_flight = []
        lock = threading.Lock()

        def fake_build(builder_body: dict) -> None:
            with lock:
                in_flight.append(1)
                max_in_flight.append(len(in_flight))
            time.sleep(0.05)
            with lock:
                in_flight.pop()

        app = model_builder.create_app(
            titanic_like, build=fake_build, models_dir="", jobs=jobs
        )
        statuses = []

        # distinct job names per request (the job is named from the
        # test filename), so nothing 409s as a duplicate
        def post_named(index: int) -> None:
            name = f"test_ds{index}"
            titanic_like.insert_one(
                name,
                {ROW_ID: METADATA_ID, "filename": name, "finished": True},
            )
            client = app.test_client()
            response = client.post(
                "/models",
                json={
                    "training_filename": "train_ds",
                    "test_filename": name,
                    "preprocessor_code": "",
                    "classificators_list": ["nb"],
                    "async": True,
                },
            )
            statuses.append(response.status_code)

        threads = [
            threading.Thread(target=post_named, args=(i,)) for i in range(6)
        ]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        # every request was either admitted (201) or refused (429) —
        # nothing else — and admitted builds NEVER ran concurrently
        assert set(statuses) <= {201, 429}
        assert statuses.count(201) >= 1
        deadline = time.time() + 30
        while any(
            job["state"] in ("pending", "running")
            for job in jobs.all_jobs()
        ):
            assert time.time() < deadline
            time.sleep(0.01)
        assert max(max_in_flight) == 1

    def test_sync_build_queues_behind_async(self, titanic_like):
        from learningorchestra_tpu.services import model_builder

        jobs = make_manager(device_width=1, queue_cap=8)
        order = []

        def fake_build(builder_body: dict) -> None:
            order.append(builder_body["test_filename"])
            time.sleep(0.05)

        app = model_builder.create_app(
            titanic_like, build=fake_build, models_dir="", jobs=jobs
        )
        client = app.test_client()
        first = client.post(
            "/models",
            json={
                "training_filename": "train_ds",
                "test_filename": "test_ds",
                "preprocessor_code": "",
                "classificators_list": ["nb"],
                "async": True,
            },
        )
        assert first.status_code == 201
        # the sync build blocks until ITS turn on the device queue ends
        titanic_like.insert_one(
            "test_ds2",
            {ROW_ID: METADATA_ID, "filename": "test_ds2", "finished": True},
        )
        second = client.post(
            "/models",
            json={
                "training_filename": "train_ds",
                "test_filename": "test_ds2",
                "preprocessor_code": "",
                "classificators_list": ["nb"],
            },
        )
        assert second.status_code == 201
        assert order.index("test_ds") < order.index("test_ds2")


# --------------------------------------------------------------------
# Satellites: eviction, wait race, knob validation
# --------------------------------------------------------------------


class TestRecordEviction:
    def test_terminal_records_evicted_by_max_count(self, monkeypatch):
        monkeypatch.setenv("LO_JOB_HISTORY", "5")
        manager = make_manager()
        # lo_jobs_total is process-global: measure the delta, not the
        # absolute (other tests in this process increment it too)
        before = manager._jobs_total.value("finished")
        for index in range(12):
            manager.submit(f"job{index}", lambda: None)
            manager.wait(f"job{index}", timeout=10)
        assert len(manager.all_jobs()) <= 5
        # the counter stayed monotonic across evictions
        assert manager._jobs_total.value("finished") - before == 12.0

    def test_terminal_records_evicted_by_ttl(self, monkeypatch):
        monkeypatch.setenv("LO_JOB_TTL_S", "0.05")
        manager = make_manager()
        manager.submit("old", lambda: None)
        manager.wait("old", timeout=10)
        time.sleep(0.1)
        manager.submit("new", lambda: None)
        manager.wait("new", timeout=10)
        names = {job["name"] for job in manager.all_jobs()}
        assert "old" not in names
        assert "new" in names

    def test_active_jobs_never_evicted(self, monkeypatch):
        monkeypatch.setenv("LO_JOB_HISTORY", "2")
        manager = make_manager(host_width=4)
        gate = threading.Event()
        for index in range(4):
            manager.submit(f"live{index}", gate.wait)
        manager.submit("one_more", lambda: None)
        names = {job["name"] for job in manager.all_jobs()}
        assert {f"live{i}" for i in range(4)} <= names
        gate.set()
        for index in range(4):
            manager.wait(f"live{index}", timeout=10)


class TestWaitRace:
    def test_wait_returns_the_record_it_waited_on(self):
        manager = make_manager()
        manager.submit("job", lambda: None)
        first = manager.wait("job", timeout=10)
        assert first.state == FINISHED
        # re-register the same name with a never-finishing job; a wait
        # started BEFORE the re-registration must still return records
        # consistently (snapshot under the lock, not two racy reads)
        gate = threading.Event()
        manager.submit("job", gate.wait)
        with pytest.raises(TimeoutError):
            manager.wait("job", timeout=0.05)
        gate.set()
        assert manager.wait("job", timeout=10).state == FINISHED

    def test_wait_unknown_job_raises_keyerror(self):
        with pytest.raises(KeyError):
            make_manager().wait("ghost", timeout=0.1)


class TestKnobValidation:
    def test_malformed_values_fail_fast(self, monkeypatch):
        monkeypatch.setenv("LO_JOB_WORKERS", "eight")
        with pytest.raises(ValueError, match="LO_JOB_WORKERS"):
            sched_config.host_width()
        monkeypatch.setenv("LO_SCHED_DEVICE_WIDTH", "0")
        with pytest.raises(ValueError, match="LO_SCHED_DEVICE_WIDTH"):
            sched_config.device_width()
        monkeypatch.setenv("LO_SCHED_QUEUE_CAP", "-3")
        with pytest.raises(ValueError, match="LO_SCHED_QUEUE_CAP"):
            sched_config.queue_cap()

    def test_valid_values_apply(self, monkeypatch):
        monkeypatch.setenv("LO_JOB_WORKERS", "3")
        monkeypatch.setenv("LO_SCHED_DEVICE_WIDTH", "2")
        monkeypatch.setenv("LO_SCHED_QUEUE_CAP", "9")
        scheduler = Scheduler()
        assert scheduler.class_width(HOST_CLASS) == 3
        assert scheduler.class_width(DEVICE_CLASS) == 2
        assert scheduler._classes[HOST_CLASS].cap == 9

    def test_cluster_manifest_sched_section(self, tmp_path):
        import sys

        sys.path.insert(0, "deploy")
        try:
            import cluster
        finally:
            sys.path.pop(0)
        path = tmp_path / "manifest.json"
        path.write_text(
            json.dumps(
                {
                    "repo": ".",
                    "head": {"host": "127.0.0.1"},
                    "sched": {"job_workers": 4, "queue_cap": 32},
                }
            )
        )
        loaded = cluster.load_manifest(str(path))
        env = cluster.machine_plans(loaded)[0]["env"]
        assert env["LO_JOB_WORKERS"] == "4"
        assert env["LO_SCHED_QUEUE_CAP"] == "32"
        bad = tmp_path / "bad.json"
        for value in ("four", 0, True):  # bool is an int subclass
            bad.write_text(
                json.dumps(
                    {
                        "repo": ".",
                        "head": {"host": "127.0.0.1"},
                        "sched": {"job_workers": value},
                    }
                )
            )
            with pytest.raises(SystemExit):
                cluster.load_manifest(str(bad))
