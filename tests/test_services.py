"""REST services: route/status/error-string parity with the reference."""

import json

import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.services import (
    data_type_handler,
    database_api,
    histogram,
    images,
    model_builder,
    projection,
)


def body(response):
    return json.loads(response.get_data())


@pytest.fixture()
def ingested(store, titanic_csv):
    write_ingest_metadata(store, "titanic", titanic_csv)
    ingest_csv(store, "titanic", titanic_csv)
    return store


class TestDatabaseApi:
    def test_create_file_async_and_read(self, store, titanic_csv):
        jobs = JobManager()
        client = database_api.create_app(store, jobs).test_client()
        response = client.post(
            "/files", json={"filename": "titanic", "url": titanic_csv}
        )
        assert response.status_code == 201
        assert body(response) == {"result": "file_created"}
        jobs.wait("ingest:titanic", timeout=30)
        response = client.get("/files/titanic?skip=0&limit=1&query={}")
        assert response.status_code == 200
        meta = body(response)["result"][0]
        assert meta["finished"] is True and meta["filename"] == "titanic"

    def test_jobs_endpoint(self, store, titanic_csv):
        jobs = JobManager()
        client = database_api.create_app(store, jobs).test_client()
        assert body(client.get("/jobs")) == {"result": []}
        client.post("/files", json={"filename": "titanic", "url": titanic_csv})
        jobs.wait("ingest:titanic", timeout=30)
        listing = body(client.get("/jobs"))["result"]
        assert len(listing) == 1
        job = listing[0]
        assert job["name"] == "ingest:titanic"
        assert job["state"] == "finished"

    def test_invalid_url_406(self, store, tmp_path):
        bad = tmp_path / "bad.html"
        bad.write_text("<html></html>")
        client = database_api.create_app(store).test_client()
        response = client.post(
            "/files", json={"filename": "x", "url": str(bad)}
        )
        assert response.status_code == 406
        assert body(response) == {"result": "invalid_url"}

    def test_duplicate_409(self, ingested, titanic_csv):
        client = database_api.create_app(ingested).test_client()
        response = client.post(
            "/files", json={"filename": "titanic", "url": titanic_csv}
        )
        assert response.status_code == 409
        assert body(response) == {"result": "duplicate_file"}

    def test_pagination_cap_20(self, store, tmp_path):
        csv = tmp_path / "wide.csv"
        csv.write_text("a\n" + "\n".join(str(i) for i in range(50)))
        jobs = JobManager()
        client = database_api.create_app(store, jobs).test_client()
        client.post("/files", json={"filename": "wide", "url": str(csv)})
        jobs.wait("ingest:wide", timeout=30)
        response = client.get("/files/wide?skip=0&limit=100&query={}")
        assert len(body(response)["result"]) == 20

    def test_read_resume_and_delete(self, ingested):
        client = database_api.create_app(ingested).test_client()
        listing = body(client.get("/files"))["result"]
        assert listing and "_id" not in listing[0]
        response = client.delete("/files/titanic")
        assert response.status_code == 200
        assert body(response) == {"result": "deleted_file"}
        assert "titanic" not in ingested.list_collections()


class TestProjection:
    def test_created(self, ingested):
        client = projection.create_app(ingested).test_client()
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "proj", "fields": ["Name", "Age"]},
        )
        assert response.status_code == 201
        assert body(response) == {"result": "created_file"}
        assert ingested.is_finished("proj")

    def test_duplicate_409(self, ingested):
        client = projection.create_app(ingested).test_client()
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "titanic", "fields": ["Name"]},
        )
        assert response.status_code == 409
        assert body(response) == {"result": "duplicate_file"}

    def test_invalid_parent_406(self, ingested):
        client = projection.create_app(ingested).test_client()
        response = client.post(
            "/projections/nope",
            json={"projection_filename": "p", "fields": ["Name"]},
        )
        assert response.status_code == 406
        assert body(response) == {"result": "invalid_filename"}

    def test_missing_and_invalid_fields_406(self, ingested):
        client = projection.create_app(ingested).test_client()
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "p", "fields": []},
        )
        assert body(response) == {"result": "missing_fields"}
        assert response.status_code == 406
        response = client.post(
            "/projections/titanic",
            json={"projection_filename": "p", "fields": ["Nope"]},
        )
        assert body(response) == {"result": "invalid_fields"}
        assert response.status_code == 406


class TestDataTypeHandler:
    def test_changed(self, ingested):
        client = data_type_handler.create_app(ingested).test_client()
        response = client.patch("/fieldtypes/titanic", json={"Age": "number"})
        assert response.status_code == 200
        assert body(response) == {"result": "file_changed"}

    def test_errors(self, ingested):
        client = data_type_handler.create_app(ingested).test_client()
        assert body(client.patch("/fieldtypes/nope", json={"Age": "number"})) == {
            "result": "invalid_filename"
        }
        assert body(client.patch("/fieldtypes/titanic", json={})) == {
            "result": "missing_fields"
        }
        assert body(
            client.patch("/fieldtypes/titanic", json={"Age": "boolean"})
        ) == {"result": "invalid_fields"}


class TestHistogram:
    def test_created(self, ingested):
        client = histogram.create_app(ingested).test_client()
        response = client.post(
            "/histograms/titanic",
            json={"histogram_filename": "hist", "fields": ["Sex"]},
        )
        assert response.status_code == 201
        assert body(response) == {"result": "created_file"}

    def test_duplicate_uses_histogram_string(self, ingested):
        client = histogram.create_app(ingested).test_client()
        response = client.post(
            "/histograms/titanic",
            json={"histogram_filename": "titanic", "fields": ["Sex"]},
        )
        assert response.status_code == 409
        assert body(response) == {"result": "duplicated_filename"}


class TestModelBuilder:
    def test_validator_errors(self, ingested):
        client = model_builder.create_app(ingested).test_client()
        response = client.post(
            "/models",
            json={
                "training_filename": "nope",
                "test_filename": "titanic",
                "preprocessor_code": "",
                "classificators_list": ["lr"],
            },
        )
        assert response.status_code == 406
        assert body(response) == {"result": "invalid_training_filename"}
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic",
                "test_filename": "nope",
                "preprocessor_code": "",
                "classificators_list": ["lr"],
            },
        )
        assert body(response) == {"result": "invalid_test_filename"}
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic",
                "test_filename": "titanic",
                "preprocessor_code": "",
                "classificators_list": ["svm"],
            },
        )
        assert body(response) == {"result": "invalid_classificator_name"}


class TestImagesService:
    @pytest.fixture()
    def numeric_store(self, store):
        from learningorchestra_tpu.core.table import ColumnTable, write_table
        import numpy as np

        rng = np.random.default_rng(0)
        table = ColumnTable.from_lists(
            {
                "a": rng.normal(size=40).tolist(),
                "b": rng.normal(size=40).tolist(),
                "Survived": rng.integers(0, 2, size=40).astype(float).tolist(),
            }
        )
        write_table(
            store,
            "numbers",
            table,
            {"filename": "numbers", "finished": True, "fields": ["a", "b", "Survived"]},
        )
        return store

    def test_pca_create_get_delete(self, numeric_store, tmp_path):
        client = images.create_app(numeric_store, str(tmp_path), "pca").test_client()
        response = client.post(
            "/images/numbers",
            json={"pca_filename": "img", "label_name": "Survived"},
        )
        assert response.status_code == 201
        assert body(response) == {"result": "created_file"}
        listing = body(client.get("/images"))["result"]
        assert listing == ["img.png"]
        response = client.get("/images/img")
        assert response.status_code == 200
        assert response.get_data()[:4] == b"\x89PNG"
        response = client.post(
            "/images/numbers", json={"pca_filename": "img", "label_name": None}
        )
        assert response.status_code == 409
        assert body(response) == {"result": "duplicate_file"}
        response = client.delete("/images/img")
        assert response.status_code == 200
        response = client.get("/images/img")
        assert response.status_code == 404
        assert body(response) == {"result": "file_not_found"}

    def test_invalid_label_406(self, numeric_store, tmp_path):
        client = images.create_app(numeric_store, str(tmp_path), "pca").test_client()
        response = client.post(
            "/images/numbers", json={"pca_filename": "i2", "label_name": "nope"}
        )
        assert response.status_code == 406
        assert body(response) == {"result": "invalid_field"}

    def test_listing_hides_inflight_claim_markers(self, numeric_store, tmp_path):
        client = images.create_app(numeric_store, str(tmp_path), "pca").test_client()
        (tmp_path / "pending.png.part").touch()  # simulated in-flight create
        assert body(client.get("/images"))["result"] == []
        response = client.get("/images/pending")
        assert response.status_code == 404

    def test_claim_never_overwrites_finished_png(self, numeric_store, tmp_path):
        client = images.create_app(numeric_store, str(tmp_path), "pca").test_client()
        response = client.post(
            "/images/numbers", json={"pca_filename": "img", "label_name": "Survived"}
        )
        assert response.status_code == 201
        rendered = (tmp_path / "img.png").read_bytes()
        # Simulate the race: name_taken() saw nothing (a concurrent
        # winner finished in the window), the marker is acquired, but the
        # PNG exists — the loser must 409 and leave the image untouched.
        import unittest.mock

        with unittest.mock.patch.object(images.os, "listdir", return_value=[]):
            response = client.post(
                "/images/numbers",
                json={"pca_filename": "img", "label_name": "Survived"},
            )
        assert response.status_code == 409
        assert body(response) == {"result": "duplicate_file"}
        assert (tmp_path / "img.png").read_bytes() == rendered
        assert not (tmp_path / "img.png.part").exists()


class TestQueryPassThrough:
    def test_operator_query_over_rest(self, ingested):
        client = database_api.create_app(ingested).test_client()
        query = json.dumps({"_id": {"$gt": 0, "$lte": 3}})
        response = client.get(f"/files/titanic?limit=20&query={query}")
        assert response.status_code == 200
        rows = body(response)["result"]
        assert [r["_id"] for r in rows] == [1, 2, 3]

    def test_in_operator_on_string_field(self, ingested):
        client = database_api.create_app(ingested).test_client()
        query = json.dumps({"Sex": {"$in": ["female"]}})
        response = client.get(f"/files/titanic?limit=20&query={query}")
        rows = body(response)["result"]
        assert rows and all(r["Sex"] == "female" for r in rows)


class TestConcurrentCreate:
    def test_duplicate_projection_one_winner(self, ingested):
        """The check-then-act race SURVEY §5 flags: concurrent duplicate
        creates must produce exactly one 201 and one 409 — never a 500."""
        import threading

        app = projection.create_app(ingested)
        results = []
        barrier = threading.Barrier(2)

        def create():
            client = app.test_client()
            barrier.wait()
            response = client.post(
                "/projections/titanic",
                json={"projection_filename": "race_proj", "fields": ["Name"]},
            )
            results.append(response.status_code)

        threads = [threading.Thread(target=create) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == [201, 409]

    def test_duplicate_histogram_one_winner(self, ingested):
        import threading

        app = histogram.create_app(ingested)
        results = []
        barrier = threading.Barrier(2)

        def create():
            client = app.test_client()
            barrier.wait()
            response = client.post(
                "/histograms/titanic",
                json={"histogram_filename": "race_hist", "fields": ["Sex"]},
            )
            results.append(response.status_code)

        threads = [threading.Thread(target=create) for _ in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == [201, 409]


class TestImageFilenameSafety:
    def test_traversal_rejected_on_create(self, store, tmp_path):
        client = images.create_app(store, str(tmp_path), "pca").test_client()
        for bad in ("../evil", "a/b", "..", ""):
            response = client.post(
                "/images/whatever", json={"pca_filename": bad, "label_name": None}
            )
            assert response.status_code == 406, bad
            assert body(response) == {"result": "invalid_filename"}
        assert list(tmp_path.parent.glob("*.png")) == []

    def test_traversal_rejected_on_get_delete(self, store, tmp_path):
        outside = tmp_path / "secret.png"
        outside.write_bytes(b"\x89PNG....")
        images_dir = tmp_path / "imgs"
        client = images.create_app(store, str(images_dir), "pca").test_client()
        response = client.get("/images/..%2Fsecret")
        assert response.status_code == 404
        response = client.delete("/images/..%2Fsecret")
        assert response.status_code == 404
        assert outside.exists()


class TestQueryErrors:
    def test_unsupported_operator_400(self, ingested):
        client = database_api.create_app(ingested).test_client()
        query = json.dumps({"Name": {"$text": "x"}})
        response = client.get(f"/files/titanic?limit=5&query={query}")
        assert response.status_code == 400
        assert "unsupported query operator" in body(response)["result"]

    def test_or_query_over_rest(self, ingested):
        client = database_api.create_app(ingested).test_client()
        query = json.dumps({"$or": [{"_id": 1}, {"_id": 4}]})
        response = client.get(f"/files/titanic?limit=20&query={query}")
        rows = body(response)["result"]
        assert [r["_id"] for r in rows] == [1, 4]


class TestInFlightImageClaim:
    def test_placeholder_invisible_to_get_and_delete(self, store, tmp_path, monkeypatch):
        """While a create is computing, GET/DELETE must 404 (no 0-byte
        PNG leak) and a concurrent duplicate POST must 409."""
        import threading

        from learningorchestra_tpu.core.table import ColumnTable, write_table
        import numpy as np

        rng = np.random.default_rng(0)
        table = ColumnTable.from_lists(
            {"a": rng.normal(size=20).tolist(), "b": rng.normal(size=20).tolist()}
        )
        write_table(
            store, "n", table, {"filename": "n", "finished": True, "fields": ["a", "b"]}
        )
        app = images.create_app(store, str(tmp_path), "pca")
        client = app.test_client()

        entered = threading.Event()
        release = threading.Event()
        import learningorchestra_tpu.services.images as images_module

        real_create = images_module.create_embedding_image

        def slow_create(*args, **kwargs):
            entered.set()
            release.wait(timeout=10)
            return real_create(*args, **kwargs)

        monkeypatch.setattr(images_module, "create_embedding_image", slow_create)

        result = {}

        def do_create():
            result["create"] = app.test_client().post(
                "/images/n", json={"pca_filename": "slow", "label_name": None}
            )

        t = threading.Thread(target=do_create)
        t.start()
        assert entered.wait(timeout=10)
        assert client.get("/images/slow").status_code == 404
        assert client.delete("/images/slow").status_code == 404
        dup = client.post("/images/n", json={"pca_filename": "slow", "label_name": None})
        assert dup.status_code == 409
        release.set()
        t.join(timeout=30)
        assert result["create"].status_code == 201
        assert client.get("/images/slow").status_code == 200
        # claim marker cleaned up
        assert sorted(p.name for p in tmp_path.iterdir()) == ["slow.png"]


class TestMalformedQueries400:
    def test_unparseable_and_nondict_queries(self, ingested):
        client = database_api.create_app(ingested).test_client()
        for bad in ("hello", "5", "[1,2]"):
            response = client.get(f"/files/titanic?limit=5&query={bad}")
            assert response.status_code == 400, bad
        response = client.get("/files/titanic?limit=abc")
        assert response.status_code == 400

    def test_malformed_operands(self, ingested):
        client = database_api.create_app(ingested).test_client()
        bads = [
            {"a": {"$nin": 5}},
            {"s": {"$regex": "("}},
            {"a": {"$not": 5}},
            {"$or": {"a": 1}},
            {"a": {"$in": 3}},
        ]
        for bad in bads:
            response = client.get(
                f"/files/titanic?limit=5&query={json.dumps(bad)}"
            )
            assert response.status_code == 400, bad


class TestAsyncModelBuild:
    @pytest.fixture()
    def store_with_numeric_dataset(self, store):
        from learningorchestra_tpu.core.table import write_columns

        write_columns(
            store,
            "numbers",
            {
                "a": [float(i % 7) for i in range(240)],
                "b": [float((i * 3) % 5) for i in range(240)],
                "label": [float(i % 2) for i in range(240)],
            },
            {"filename": "numbers", "finished": True,
             "fields": ["a", "b", "label"]},
        )
        return store

    def test_async_build_returns_immediately_and_tracks_job(
        self, store_with_numeric_dataset
    ):
        import json as _json
        import time as _time

        from learningorchestra_tpu.services import model_builder

        store = store_with_numeric_dataset
        app = model_builder.create_app(store).test_client()
        body = {
            "training_filename": "numbers",
            "test_filename": "numbers",
            "preprocessor_code": (
                "from pyspark.ml.feature import VectorAssembler\n"
                "assembler = VectorAssembler(inputCols=['a', 'b'],"
                " outputCol='features')\n"
                "features_training = assembler.transform(training_df)\n"
                "features_testing = assembler.transform(testing_df)\n"
                "features_evaluation = None\n"
            ),
            "classificators_list": ["nb"],
            "async": True,
        }
        response = app.post("/models", json=body)
        assert response.status_code == 201
        payload = _json.loads(response.get_data())
        job_name = payload["job"]

        deadline = _time.time() + 120
        while _time.time() < deadline:
            jobs = _json.loads(app.get("/jobs").get_data())["result"]
            record = next(j for j in jobs if j["name"] == job_name)
            if record["state"] in ("finished", "failed"):
                break
            _time.sleep(0.2)
        else:
            raise AssertionError(f"async build never completed: {record}")
        assert record["state"] == "finished", record
        assert "numbers_prediction_nb" in store.list_collections()

    def test_async_build_failure_reported_in_jobs(
        self, store_with_numeric_dataset
    ):
        import json as _json
        import time as _time

        from learningorchestra_tpu.services import model_builder

        store = store_with_numeric_dataset
        app = model_builder.create_app(store).test_client()
        response = app.post(
            "/models",
            json={
                "training_filename": "numbers",
                "test_filename": "numbers",
                "preprocessor_code": "this is not python",
                "classificators_list": ["nb"],
                "async": True,
            },
        )
        assert response.status_code == 201
        deadline = _time.time() + 60
        while _time.time() < deadline:
            jobs = _json.loads(app.get("/jobs").get_data())["result"]
            record = jobs[-1]
            if record["state"] in ("finished", "failed"):
                break
            _time.sleep(0.2)
        assert record["state"] == "failed"
        assert record["error"]
