"""SPMD-safety + concurrency analyzer: per-rule fixtures, CLI, repo gate.

Every rule family (LO101–LO104 SPMD safety, LO201–LO206 concurrency
hazards) gets at least one positive (bad code the rule must flag), one
negative (the nearby good idiom it must NOT flag), and one suppressed
fixture. The gate at the bottom runs the analyzer over the real source
trees and asserts zero non-baselined findings — the invariant the
analyzer exists to enforce on every PR.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys
import textwrap

import pytest

from learningorchestra_tpu.analysis import analyze_source
from learningorchestra_tpu.analysis.cli import main as cli_main

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings_for(source: str, select=None):
    return analyze_source(textwrap.dedent(source), "probe.py", select)


def rules_of(source: str) -> set:
    return {finding.rule for finding in findings_for(source)}


# --------------------------------------------------------------------
# LO101 — collective divergence
# --------------------------------------------------------------------


class TestLO101CollectiveDivergence:
    def test_jnp_dispatch_under_coordinator_guard(self):
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator):
                if coordinator:
                    return jnp.sum(payload["x"])
        """
        assert "LO101" in rules_of(src)

    def test_collective_under_write_outputs_guard(self):
        src = """
            def handler(model, write_outputs):
                if write_outputs:
                    gathered = gather_model(model)
        """
        assert "LO101" in rules_of(src)

    def test_early_return_guard_poisons_rest_of_function(self):
        # `if process_index() != 0: return` makes everything after it
        # coordinator-only — the deadlock shape without any indentation
        src = """
            import jax

            def handler(model, payload):
                if jax.process_index() != 0:
                    return
                model.fit(payload)
        """
        assert "LO101" in rules_of(src)

    def test_else_branch_is_equally_divergent(self):
        src = """
            def handler(dispatcher, payload, coordinator):
                if coordinator:
                    pass
                else:
                    dispatcher.submit("op", payload)
        """
        assert "LO101" in rules_of(src)

    def test_host_writes_under_guard_are_fine(self):
        src = """
            def handler(store, metadata, write_outputs):
                if write_outputs:
                    store.insert_one("out", metadata)
        """
        assert rules_of(src) == set()

    def test_collective_outside_guard_is_fine(self):
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator):
                total = jnp.sum(payload["x"])
                if coordinator:
                    print(total)
        """
        assert rules_of(src) == set()

    def test_process_count_is_not_a_divergence_guard(self):
        # process_count is identical on every process — `if
        # jax.process_count() == 1` selects a MODE, not a subset of
        # processes
        src = """
            import jax
            import jax.numpy as jnp

            def handler(payload):
                if jax.process_count() == 1:
                    return jnp.sum(payload["x"])
        """
        assert rules_of(src) == set()

    def test_def_under_guard_not_flagged(self):
        # a closure defined under a guard runs on its own schedule
        src = """
            import jax

            def start(submit):
                if jax.process_index() != 0:
                    return

                def beat():
                    return _broadcast_json({"op": "ping"})
                return beat
        """
        assert rules_of(src) == set()

    def test_while_loop_guard_is_divergent(self):
        # a coordinator-only polling loop is the same deadlock shape
        # as an if-guard, without the if
        src = """
            import jax

            def poll(dispatcher, payload):
                while jax.process_index() == 0:
                    dispatcher.submit("op", payload)
        """
        assert "LO101" in rules_of(src)

    def test_while_else_runs_on_every_process(self):
        src = """
            def run(coordinator, log):
                while coordinator:
                    log.flush()
                else:
                    _broadcast_json({"op": "sync"})
        """
        assert rules_of(src) == set()

    def test_conditional_expression_guard_is_divergent(self):
        src = """
            def run(model, coordinator):
                gathered = gather_model(model) if coordinator else None
                return gathered
        """
        assert "LO101" in rules_of(src)

    def test_short_circuit_and_guard_is_divergent(self):
        # `coordinator and gather(...)`: short-circuiting makes the
        # collective coordinator-only with no if statement at all
        src = """
            def run(model, coordinator):
                ok = coordinator and gather_model(model)
                return ok
        """
        assert "LO101" in rules_of(src)

    def test_short_circuit_collective_before_guard_is_fine(self):
        # evaluation order matters: the collective runs on EVERY
        # process here, the divergent name only gates the result
        src = """
            def run(model, coordinator):
                ok = gather_model(model) and coordinator
                return ok
        """
        assert rules_of(src) == set()

    def test_nested_guards_report_once(self):
        # guards nesting through a non-If compound statement must not
        # double-count one defect (one finding, two baseline entries)
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator, write_outputs, lock):
                if coordinator:
                    with lock:
                        if write_outputs:
                            return jnp.sum(payload["x"])
        """
        found = [f for f in findings_for(src) if f.rule == "LO101"]
        assert len(found) == 1

    def test_inline_allow_comment_suppresses(self):
        src = """
            def shutdown(coordinator):
                if coordinator:
                    _broadcast_json({"op": "x"})  # lo: allow[LO101]
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO102 — broadcast determinism
# --------------------------------------------------------------------


class TestLO102BroadcastDeterminism:
    def test_wall_clock_through_assignment_into_submit(self):
        # the shape of the ml/builder.py trace-dir bug (this rule's
        # motivating example): a wall-clock value laundered through an
        # f-string and a dict before reaching the payload
        src = """
            import time

            def run(dispatcher):
                stamp = int(time.time() * 1000)
                payload = {"dir": f"build_{stamp}"}
                dispatcher.submit("build_model", payload)
        """
        assert "LO102" in rules_of(src)

    def test_unseeded_random_direct_into_broadcast(self):
        src = """
            import random

            def run():
                _broadcast_json({"seed": random.random()})
        """
        assert "LO102" in rules_of(src)

    def test_unseeded_default_rng_flagged(self):
        src = """
            import numpy as np

            def run():
                _broadcast_json({"draw": np.random.default_rng().random()})
        """
        assert "LO102" in rules_of(src)

    def test_assigned_unseeded_rng_flagged_through_method_call(self):
        # the common spelling: construct once, draw later — receiver
        # taint must ride through the method call
        src = """
            import numpy as np

            def run():
                rng = np.random.default_rng()
                _broadcast_json({"draw": rng.random()})
        """
        assert "LO102" in rules_of(src)

    def test_set_iteration_order_flagged(self):
        src = """
            def run(names):
                _broadcast_json(list(set(names)))
        """
        assert "LO102" in rules_of(src)

    def test_tuple_assignment_carries_taint(self):
        # the motivating bug spelled as a tuple assign must not slip
        # through the single-Name fast path
        src = """
            import time

            def run(dispatcher):
                stamp, other = time.time(), 1
                dispatcher.submit("op", {"t": stamp})
        """
        assert "LO102" in rules_of(src)

    def test_tuple_assignment_untainted_element_is_fine(self):
        src = """
            import time

            def run(dispatcher):
                stamp, other = time.time(), 1
                dispatcher.submit("op", {"n": other})
        """
        assert rules_of(src) == set()

    def test_unpacking_single_tainted_value_taints_all_names(self):
        src = """
            import time

            def run(dispatcher):
                minutes, seconds = divmod(time.time(), 60)
                _broadcast_json({"s": seconds})
        """
        assert "LO102" in rules_of(src)

    def test_for_tuple_target_carries_set_iteration_taint(self):
        src = """
            def run(pairs):
                for key, value in set(pairs):
                    _broadcast_json({"k": key})
        """
        assert "LO102" in rules_of(src)

    def test_rebind_inside_branch_clears_taint_before_sink(self):
        # the sink sees the env AFTER the branch's own rebind — a
        # false positive here would hard-fail the deploy preflight on
        # correct code
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                    _broadcast_json({"op": x})
        """
        assert rules_of(src) == set()

    def test_sink_after_branch_still_sees_outer_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    pass
                _broadcast_json({"op": x})
        """
        assert "LO102" in rules_of(src)

    def test_taint_from_one_branch_survives_the_join(self):
        # conditionally tainted IS tainted: one process takes the
        # clock branch, another doesn't — the payloads diverge
        src = """
            import time

            def run(cond):
                if cond:
                    x = time.time()
                else:
                    x = 1
                _broadcast_json({"t": x})
        """
        assert "LO102" in rules_of(src)

    def test_branch_rebind_does_not_erase_fallthrough_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                _broadcast_json({"t": x})
        """
        assert "LO102" in rules_of(src)

    def test_rebind_on_every_path_clears_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                else:
                    x = 2
                _broadcast_json({"t": x})
        """
        assert rules_of(src) == set()

    def test_sorted_set_is_deterministic(self):
        src = """
            def run(names):
                _broadcast_json(sorted(set(names)))
        """
        assert rules_of(src) == set()

    def test_seeded_rng_is_fine(self):
        src = """
            import numpy as np

            def run(seed):
                rng = np.random.default_rng(seed)
                _broadcast_json({"draw": float(rng.random())})
        """
        assert rules_of(src) == set()

    def test_clock_used_locally_is_fine(self):
        src = """
            import time

            def run(dispatcher, payload):
                start = time.time()
                dispatcher.submit("op", payload)
                return time.time() - start
        """
        assert rules_of(src) == set()

    def test_non_dispatcher_submit_not_a_sink(self):
        src = """
            import time

            def run(pool, fit):
                pool.submit(fit, time.time())
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO103 — trace safety
# --------------------------------------------------------------------


class TestLO103TraceSafety:
    def test_float_on_traced_value_in_jit(self):
        src = """
            import jax

            @jax.jit
            def fn(x):
                return float(x.sum())
        """
        assert "LO103" in rules_of(src)

    def test_item_and_print_in_partial_jit(self):
        src = """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def fn(x, n):
                print(x)
                return x.item()
        """
        findings = findings_for(src)
        assert sum(f.rule == "LO103" for f in findings) == 2

    def test_numpy_call_in_jit_wrapped_function(self):
        src = """
            import jax
            import numpy as np

            def fn(x):
                return np.asarray(x)

            fast = jax.jit(fn)
        """
        assert "LO103" in rules_of(src)

    def test_nested_def_inside_jit_is_traced_too(self):
        src = """
            import jax

            @jax.jit
            def outer(x):
                def inner(v):
                    return float(v)
                return inner(x)
        """
        assert "LO103" in rules_of(src)

    def test_static_shape_math_is_fine(self):
        src = """
            import jax

            @jax.jit
            def fn(x):
                n = int(x.shape[0] * 2)
                m = float(len(x.shape))
                return x.reshape(n // 2, -1) * m
        """
        assert rules_of(src) == set()

    def test_same_calls_outside_jit_are_fine(self):
        src = """
            import numpy as np

            def host_fn(x):
                print(x)
                return float(np.asarray(x).sum())
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO104 — dtype hygiene
# --------------------------------------------------------------------


class TestLO104DtypeHygiene:
    def test_np_float64_in_jit(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def fn(x):
                return x.astype(np.float64)
        """
        assert "LO104" in rules_of(src)

    def test_float64_string_dtype_in_jit(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fn(n):
                return jnp.zeros(3, dtype="float64")
        """
        assert "LO104" in rules_of(src)

    def test_jnp_float64_dtype_outside_jit(self):
        # op-by-op dispatch is device code even without @jit
        src = """
            import jax.numpy as jnp
            import numpy as np

            def fn(values):
                return jnp.asarray(values, dtype=np.float64)
        """
        assert "LO104" in rules_of(src)

    def test_host_side_float64_is_fine(self):
        # the store's column format IS float64 — host paths are exempt
        src = """
            import numpy as np

            def to_column(values):
                return np.asarray(values, dtype=np.float64)
        """
        assert rules_of(src) == set()

    def test_default_dtypes_in_jit_are_fine(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fn(x):
                return jnp.zeros_like(x) + jnp.float32(1.0)
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO106 — hot-path host copies in core/
# --------------------------------------------------------------------

_CORE_PATH = "learningorchestra_tpu/core/probe.py"


def core_rules_of(source: str) -> set:
    return {
        finding.rule
        for finding in analyze_source(textwrap.dedent(source), _CORE_PATH)
    }


class TestLO106HostCopy:
    def test_frombuffer_copy_in_core_flagged(self):
        src = """
            import numpy as np

            def decode(raw):
                return np.frombuffer(raw, dtype=np.float64).copy()
        """
        assert "LO106" in core_rules_of(src)

    def test_chained_reshape_copy_flagged(self):
        # frombuffer(b).reshape(-1, w).copy() is the same double pass
        src = """
            import numpy as np

            def decode(raw, width):
                return np.frombuffer(raw, np.float64).reshape(-1, width).copy()
        """
        assert "LO106" in core_rules_of(src)

    def test_tobytes_in_core_flagged(self):
        src = """
            def encode(column):
                return column.data.tobytes()
        """
        assert "LO106" in core_rules_of(src)

    def test_outside_core_not_flagged(self):
        # the rule is path-gated: the same code in ml/ is out of scope
        src = """
            import numpy as np

            def decode(raw):
                return np.frombuffer(raw, dtype=np.float64).copy()
        """
        assert "LO106" not in {
            finding.rule
            for finding in analyze_source(
                textwrap.dedent(src), "learningorchestra_tpu/ml/probe.py"
            )
        }

    def test_plain_copy_not_flagged(self):
        # .copy() on an owned array is not the wire-decode double pass
        src = """
            import numpy as np

            def dup(array):
                return array.copy()
        """
        assert core_rules_of(src) == set()

    def test_view_handoff_not_flagged(self):
        # the fixed idiom: frombuffer view + reshape, no copy
        src = """
            import numpy as np

            def decode(raw, width):
                return np.frombuffer(raw, np.float64).reshape(-1, width)
        """
        assert core_rules_of(src) == set()

    def test_suppression(self):
        src = """
            import numpy as np

            def decode(raw):
                # lo: allow[LO106]
                return np.frombuffer(raw, dtype=np.uint8).copy()
        """
        assert core_rules_of(src) == set()


# --------------------------------------------------------------------
# LO201 — lock acquisition order
# --------------------------------------------------------------------


class TestLO201LockOrder:
    def test_inconsistent_order_across_methods(self):
        src = """
            class S:
                def a(self):
                    with self._lock:
                        with self._io_lock:
                            pass

                def b(self):
                    with self._io_lock:
                        with self._lock:
                            pass
        """
        assert "LO201" in rules_of(src)

    def test_consistent_nesting_is_fine(self):
        src = """
            class S:
                def a(self):
                    with self._lock:
                        with self._io_lock:
                            pass

                def b(self):
                    with self._lock:
                        with self._io_lock:
                            pass
        """
        assert rules_of(src) == set()

    def test_self_nesting_flagged(self):
        src = """
            def run(self):
                with self._lock:
                    with self._lock:
                        pass
        """
        assert "LO201" in rules_of(src)

    def test_registry_rank_violation(self):
        # devcache's _TOKEN_LOCK (rank 50) must never be held OUTSIDE
        # its _GLOBAL_LOCK (rank 40) — the declared cross-module order
        src = """
            def mint():
                with _TOKEN_LOCK:
                    with _GLOBAL_LOCK:
                        pass
        """
        findings = analyze_source(
            textwrap.dedent(src),
            "learningorchestra_tpu/core/devcache.py",
        )
        assert any(
            f.rule == "LO201" and "registry" in f.message for f in findings
        )

    def test_registry_conformant_nesting_is_fine(self):
        src = """
            def mint():
                with _GLOBAL_LOCK:
                    with _TOKEN_LOCK:
                        pass
        """
        findings = analyze_source(
            textwrap.dedent(src),
            "learningorchestra_tpu/core/devcache.py",
        )
        # the nesting edge alone never fires without a reverse edge
        assert [f for f in findings if f.rule == "LO201"] == []

    def test_non_lock_context_is_not_an_acquisition(self):
        src = """
            def run(self):
                with self._lock:
                    with span("store:read"):
                        pass
                with span("h2d"):
                    with self._lock:
                        pass
        """
        assert rules_of(src) == set()

    def test_closure_under_lock_resets_context(self):
        # a def under a with runs later, on its own thread — its
        # acquisitions are not nested inside the enclosing lock
        src = """
            class S:
                def a(self):
                    with self._lock:
                        def later():
                            with self._io_lock:
                                with self._lock:
                                    pass
                        return later
        """
        # later() does nest _io_lock → _lock; but there is no reverse
        # edge, so nothing fires — the point is the ENCLOSING with does
        # not create a _lock → _io_lock edge
        findings = [f for f in findings_for(src) if f.rule == "LO201"]
        assert findings == [] or all(
            "self-deadlock" not in f.message for f in findings
        )

    def test_lock_registry_entries_point_at_real_locks(self):
        """The declared registry must not rot: every entry names a
        module that exists in this repo and a lock that module still
        defines."""
        from learningorchestra_tpu.analysis.concurrency import LOCK_REGISTRY

        package_root = os.path.join(_REPO_ROOT, "learningorchestra_tpu")
        for (suffix, lock), rank in LOCK_REGISTRY.items():
            assert isinstance(rank, int)
            path = os.path.join(package_root, *suffix.split("/"))
            assert os.path.isfile(path), f"registry names missing {suffix}"
            with open(path, encoding="utf-8") as handle:
                assert lock in handle.read(), (
                    f"{suffix} no longer defines {lock}"
                )

    def test_inline_allow_comment_suppresses(self):
        src = """
            def run(self):
                with self._lock:
                    with self._lock:  # lo: allow[LO201]
                        pass
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO202 — blocking calls under a held lock
# --------------------------------------------------------------------


class TestLO202BlockingUnderLock:
    def test_sleep_under_lock(self):
        src = """
            import time

            def run(self):
                with self._lock:
                    time.sleep(1.0)
        """
        assert "LO202" in rules_of(src)

    def test_network_call_under_lock(self):
        src = """
            import requests

            def probe(self, url):
                with self._lock:
                    return requests.get(url, timeout=2)
        """
        assert "LO202" in rules_of(src)

    def test_store_wire_call_under_lock(self):
        # the PR 7 shape: a registry lock held across a checkpoint /
        # store operation stalls every status probe behind it
        src = """
            def finalize(self, store, collection, error):
                with self._lock:
                    store.update_one(collection, {"_id": 0}, {"e": error})
        """
        assert "LO202" in rules_of(src)

    def test_checkpoint_load_under_lock(self):
        src = """
            def get(self, path):
                with self._lock:
                    return load_model(path, mesh=self._mesh)
        """
        assert "LO202" in rules_of(src)

    def test_thread_join_under_lock(self):
        src = """
            def stop(self):
                with role["lock"]:
                    self._thread.join()
        """
        assert "LO202" in rules_of(src)

    def test_worker_stop_under_lock(self):
        # the promote_role bug this PR fixed: poller.stop() (a thread
        # join bounded only by the poll timeout) under role["lock"]
        src = """
            def promote(self, role):
                with role["lock"]:
                    poller = role.get("poller")
                    if poller is not None:
                        poller.stop()
        """
        assert "LO202" in rules_of(src)

    def test_unbounded_queue_get_under_lock(self):
        src = """
            def drain(self):
                with self._lock:
                    item = self._queue.get()
        """
        assert "LO202" in rules_of(src)

    def test_string_and_path_join_are_fine(self):
        src = """
            import os

            def render(self):
                with self._lock:
                    text = ", ".join(self._parts)
                    path = os.path.join(self._root, "x")
                return text, path
        """
        assert rules_of(src) == set()

    def test_condvar_wait_on_held_lock_is_not_lo202(self):
        # waiting on the held lock's own condition RELEASES it — that
        # is LO204's discipline, not a blocking hazard
        src = """
            def pop(self):
                with self.cond:
                    while not self.items:
                        self.cond.wait(1.0)
                    return self.items.pop()
        """
        assert rules_of(src) == set()

    def test_bounded_foreign_wait_is_fine(self):
        src = """
            def submit(self, done):
                with self._lock:
                    done.wait(30.0)
        """
        assert rules_of(src) == set()

    def test_self_store_methods_exempt(self):
        # the in-memory store's re-entrant internal calls under its own
        # RLock are its design, not a wire round trip
        src = """
            def insert_many(self, collection, documents):
                with self._lock:
                    for document in documents:
                        self.insert_one(collection, document)
        """
        assert rules_of(src) == set()

    def test_blocking_call_outside_lock_is_fine(self):
        src = """
            import time

            def run(self):
                with self._lock:
                    payload = self._next()
                time.sleep(0.1)
                return payload
        """
        assert rules_of(src) == set()

    def test_inline_allow_comment_suppresses(self):
        src = """
            def apply(self, records):
                with self._apply_lock:
                    self.store.apply_replicated(records)  # lo: allow[LO202]
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO203 — unguarded shared state (lockset-lite)
# --------------------------------------------------------------------


class TestLO203UnguardedSharedState:
    def test_wait_snapshot_race_shape(self):
        # THE golden case (PR 3, core/jobs.py): wait() read the maps
        # without the lock that every writer holds — a concurrent
        # re-registration paired the old event with the new record
        src = """
            class JobManager:
                def register(self, name, record):
                    with self._lock:
                        self._jobs[name] = record

                def wait(self, name):
                    return self._jobs[name]
        """
        assert "LO203" in rules_of(src)

    def test_bare_write_flagged_too(self):
        # the batcher-counter shape: written bare on the worker thread,
        # read under the lock by stats()
        src = """
            class B:
                def work(self):
                    self.batches += 1

                def stats(self):
                    with self._lock:
                        return self.batches
        """
        assert "LO203" in rules_of(src)

    def test_snapshot_under_lock_is_fine(self):
        src = """
            class JobManager:
                def register(self, name, record):
                    with self._lock:
                        self._jobs[name] = record

                def wait(self, name):
                    with self._lock:
                        return self._jobs[name]
        """
        assert rules_of(src) == set()

    def test_locked_suffix_convention(self):
        # the codebase's _locked idiom: the helper's name IS the
        # caller-holds-the-lock contract
        src = """
            class Cache:
                def put(self, key, value):
                    with self._lock:
                        self._drop_locked(key)
                        self._entries[key] = value

                def _drop_locked(self, key):
                    self._entries.pop(key, None)
        """
        assert rules_of(src) == set()

    def test_init_writes_exempt(self):
        src = """
            class Cache:
                def __init__(self):
                    self._entries = {}

                def put(self, key, value):
                    with self._lock:
                        self._entries[key] = value
        """
        assert rules_of(src) == set()

    def test_read_only_config_attr_is_fine(self):
        src = """
            class Cache:
                def put(self, key, nbytes):
                    with self._lock:
                        if nbytes <= self.capacity:
                            self._entries[key] = nbytes

                def fits(self, nbytes):
                    return nbytes <= self.capacity
        """
        assert rules_of(src) == set()

    def test_lock_attributes_themselves_exempt(self):
        src = """
            class S:
                def a(self):
                    with self._lock:
                        self._items.append(1)

                def lock_for_tests(self):
                    return self._lock
        """
        assert rules_of(src) == set()

    def test_inline_allow_comment_suppresses(self):
        src = """
            class D:
                def mark(self, reason):
                    with self._lock:
                        self._poisoned = reason

                def fast_path(self):
                    return self._poisoned  # lo: allow[LO203]
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO204 — condition-variable discipline
# --------------------------------------------------------------------


class TestLO204CondvarDiscipline:
    def test_wait_outside_predicate_loop(self):
        src = """
            def take(self):
                with self.cond:
                    self.cond.wait(1.0)
                    return self.items.pop()
        """
        assert "LO204" in rules_of(src)

    def test_wait_without_timeout(self):
        src = """
            def take(self):
                with self.cond:
                    while not self.items:
                        self.cond.wait()
                    return self.items.pop()
        """
        assert "LO204" in rules_of(src)

    def test_disciplined_wait_is_fine(self):
        src = """
            def take(self):
                with self.cond:
                    while not self.items:
                        self.cond.wait(1.0)
                    return self.items.pop()
        """
        assert rules_of(src) == set()

    def test_deadline_loop_with_timeout_is_fine(self):
        # the sync-repl ack shape: while True + internal deadline
        # checks IS a predicate loop
        src = """
            def await_shipped(self, cv, deadline):
                import time

                with cv:
                    while True:
                        if self.shipped:
                            return True
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            return False
                        cv.wait(remaining)
        """
        assert rules_of(src) == set()

    def test_notify_outside_lock(self):
        src = """
            def publish(self, item):
                self.items.append(item)
                self.cond.notify_all()
        """
        assert "LO204" in rules_of(src)

    def test_notify_under_lock_is_fine(self):
        src = """
            def publish(self, item):
                with self.cond:
                    self.items.append(item)
                    self.cond.notify_all()
        """
        assert rules_of(src) == set()

    def test_event_wait_is_not_a_condvar(self):
        src = """
            def run_sync(self, done):
                done.wait()
        """
        assert rules_of(src) == set()

    def test_inline_allow_comment_suppresses(self):
        src = """
            def take(self):
                with self.cond:
                    self.cond.wait(1.0)  # lo: allow[LO204]
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO205 — torn publish across lock scopes
# --------------------------------------------------------------------


class TestLO205TornPublish:
    def test_same_attr_mutated_in_two_scopes(self):
        # the _finalize/DELETE shape (PR 3): record and task published
        # under separate acquisitions let a cancel() between them 202 a
        # cancellation that never flips the token
        src = """
            class M:
                def publish(self, name, record, task):
                    with self._lock:
                        self._records[name] = record
                    self._journal(name)
                    with self._lock:
                        self._records[name] = task
        """
        assert "LO205" in rules_of(src)

    def test_mutating_method_calls_count(self):
        src = """
            class M:
                def rotate(self, name):
                    with self._lock:
                        self._tasks.pop(name, None)
                    with self._lock:
                        self._tasks.update({name: 1})
        """
        assert "LO205" in rules_of(src)

    def test_one_finding_per_attr_not_per_block(self):
        src = """
            class M:
                def publish(self, name):
                    with self._lock:
                        self._records[name] = 1
                    with self._lock:
                        self._records[name] = 2
                    with self._lock:
                        self._records[name] = 3
        """
        assert sum(f.rule == "LO205" for f in findings_for(src)) == 1

    def test_disjoint_attrs_are_fine(self):
        # the registry.get shape: counters in the probe scope, entries
        # in the publish scope — no attr spans both
        src = """
            class R:
                def get(self, key):
                    with self._lock:
                        self.misses += 1
                    value = self._load(key)
                    with self._lock:
                        self._entries[key] = value
                    return value
        """
        assert rules_of(src) == set()

    def test_reads_between_scopes_are_fine(self):
        src = """
            class R:
                def stats(self):
                    with self._lock:
                        count = len(self._entries)
                    with self._lock:
                        return count + len(self._entries)
        """
        assert rules_of(src) == set()

    def test_different_methods_not_torn(self):
        src = """
            class R:
                def a(self):
                    with self._lock:
                        self._entries["a"] = 1

                def b(self):
                    with self._lock:
                        self._entries["b"] = 2
        """
        assert rules_of(src) == set()

    def test_inline_allow_comment_suppresses(self):
        src = """
            class M:
                def publish(self, name, record, task):
                    with self._lock:
                        self._records[name] = record
                    self._journal(name)
                    with self._lock:  # lo: allow[LO205]
                        self._records[name] = task
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO206 — untimed HTTP / silent broad except on service edges
# --------------------------------------------------------------------

_SERVICE_PATH = "learningorchestra_tpu/services/probe.py"


def service_rules_of(source: str) -> set:
    return {
        finding.rule
        for finding in analyze_source(textwrap.dedent(source), _SERVICE_PATH)
    }


class TestLO206ServiceEdges:
    def test_untimed_requests_call_flagged(self):
        src = """
            import requests

            def probe(url):
                return requests.get(url)
        """
        assert "LO206" in service_rules_of(src)

    def test_untimed_urlopen_flagged(self):
        src = """
            from urllib.request import urlopen

            def fetch(url):
                return urlopen(url).read()
        """
        assert "LO206" in service_rules_of(src)

    def test_timed_call_not_flagged(self):
        src = """
            import requests

            def probe(url):
                return requests.post(url, json={}, timeout=5)
        """
        assert "LO206" not in service_rules_of(src)

    def test_silent_broad_except_flagged(self):
        src = """
            def probe(call):
                try:
                    call()
                except Exception:
                    pass
        """
        assert "LO206" in service_rules_of(src)

    def test_bare_except_pass_flagged(self):
        src = """
            def probe(call):
                try:
                    call()
                except:
                    pass
        """
        assert "LO206" in service_rules_of(src)

    def test_handled_broad_except_not_flagged(self):
        # swallowing is the hazard, not breadth: a handler that records
        # the failure is the documented best-effort idiom
        src = """
            import traceback

            def probe(call):
                try:
                    call()
                except Exception:
                    traceback.print_exc()
        """
        assert "LO206" not in service_rules_of(src)

    def test_client_module_in_scope(self):
        src = """
            import requests

            def probe(url):
                return requests.get(url)
        """
        findings = analyze_source(
            textwrap.dedent(src), "learningorchestra_tpu/client.py"
        )
        assert "LO206" in {finding.rule for finding in findings}

    def test_core_module_out_of_scope(self):
        # path-gated: library/store code keeps its own error contracts
        src = """
            import requests

            def probe(url):
                return requests.get(url)
        """
        findings = analyze_source(
            textwrap.dedent(src), "learningorchestra_tpu/core/probe.py"
        )
        assert "LO206" not in {finding.rule for finding in findings}

    def test_inline_allow_comment_suppresses(self):
        src = """
            import requests

            def probe(url):
                return requests.get(url)  # lo: allow[LO206]
        """
        assert "LO206" not in service_rules_of(src)


# --------------------------------------------------------------------
# CLI contract + baseline workflow
# --------------------------------------------------------------------

_BAD_MODULE = """\
import time

def run(dispatcher):
    dispatcher.submit("op", {"stamp": time.time()})
"""


_BAD_BY_RULE = {
    "LO101": (
        "import jax.numpy as jnp\n"
        "def handler(payload, coordinator):\n"
        "    if coordinator:\n"
        "        return jnp.sum(payload['x'])\n"
    ),
    "LO102": _BAD_MODULE,
    "LO103": (
        "import jax\n"
        "@jax.jit\n"
        "def fn(x):\n"
        "    return float(x.sum())\n"
    ),
    "LO104": (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def fn(v):\n"
        "    return jnp.asarray(v, dtype=np.float64)\n"
    ),
    "LO201": (
        "class S:\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            with self._io_lock:\n"
        "                pass\n"
        "    def b(self):\n"
        "        with self._io_lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    ),
    "LO202": (
        "import time\n"
        "def run(self):\n"
        "    with self._lock:\n"
        "        time.sleep(1.0)\n"
    ),
    "LO203": (
        "class M:\n"
        "    def put(self, k, v):\n"
        "        with self._lock:\n"
        "            self._jobs[k] = v\n"
        "    def wait(self, k):\n"
        "        return self._jobs[k]\n"
    ),
    "LO204": (
        "def take(self):\n"
        "    with self.cond:\n"
        "        self.cond.wait(1.0)\n"
    ),
    "LO205": (
        "class M:\n"
        "    def publish(self, name, a, b):\n"
        "        with self._lock:\n"
        "            self._records[name] = a\n"
        "        log(name)\n"
        "        with self._lock:\n"
        "            self._records[name] = b\n"
    ),
    "LO106": (
        "import numpy as np\n"
        "def decode(raw):\n"
        "    return np.frombuffer(raw, dtype=np.float64).copy()\n"
    ),
}


class TestCli:
    @pytest.mark.parametrize("rule", sorted(_BAD_BY_RULE))
    def test_each_rule_family_fails_the_cli(self, rule, tmp_path, capsys):
        # a core/ subdir so the path-gated LO106 is in scope; the other
        # rules are path-independent
        (tmp_path / "core").mkdir(exist_ok=True)
        path = tmp_path / "core" / "bad.py"
        path.write_text(_BAD_BY_RULE[rule])
        assert cli_main([str(path)]) == 1
        assert rule in capsys.readouterr().out

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        path = tmp_path / "clean.py"
        path.write_text("def fn():\n    return 1\n")
        assert cli_main([str(path)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_findings_exit_nonzero_with_location_format(
        self, tmp_path, capsys
    ):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path)]) == 1
        out = capsys.readouterr().out
        assert ":4: LO102 " in out  # file:line: LOxxx message

    def test_syntax_error_reported_not_crashed(self, tmp_path, capsys):
        path = tmp_path / "broken.py"
        path.write_text("def fn(:\n")
        assert cli_main([str(path)]) == 1
        assert "LO000" in capsys.readouterr().out

    def test_select_filters_rules(self, tmp_path):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path), "--select", "LO103"]) == 0
        assert cli_main([str(path), "--select", "LO102"]) == 1

    def test_unknown_rule_and_missing_path_are_usage_errors(self, tmp_path):
        path = tmp_path / "x.py"
        path.write_text("pass\n")
        assert cli_main([str(path), "--select", "LO999"]) == 2
        assert cli_main([str(tmp_path / "missing.py")]) == 2

    def test_select_with_trailing_comma_stays_filtered(self, tmp_path):
        # "LO103, " must not smuggle in an empty token that
        # prefix-matches every rule
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)  # violates LO102 only
        assert cli_main([str(path), "--select", "LO103, "]) == 0
        assert cli_main([str(path), "--select", " , "]) == 2

    def test_explicit_file_without_py_suffix_is_analyzed(
        self, tmp_path, capsys
    ):
        # a green run that silently skipped the named file would be
        # worse than a usage error
        path = tmp_path / "job_script"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path)]) == 1
        assert "LO102" in capsys.readouterr().out

    def test_write_baseline_with_select_is_refused(self, tmp_path):
        # a filtered write would truncate other rules' grandfathered
        # entries and break the next full preflight
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        assert (
            cli_main(
                [str(path), "--baseline", str(baseline),
                 "--write-baseline", "--select", "LO101"]
            )
            == 2
        )
        assert not baseline.exists()

    def test_missing_explicit_baseline_is_a_usage_error(self, tmp_path):
        path = tmp_path / "x.py"
        path.write_text("pass\n")
        assert (
            cli_main([str(path), "--baseline", str(tmp_path / "nope.txt")])
            == 2
        )
        # --write-baseline CREATES the file, so a missing path is fine
        assert (
            cli_main(
                [str(path), "--baseline", str(tmp_path / "new.txt"),
                 "--write-baseline"]
            )
            == 0
        )

    def test_directory_walk_skips_hidden_and_vendored_dirs(
        self, tmp_path, capsys
    ):
        # .venv / build / *.egg-info under an analyzed directory are
        # third-party or generated code the gate must not lint
        for vendored in (".venv/site-packages", "build", "pkg.egg-info"):
            target = tmp_path / vendored
            target.mkdir(parents=True)
            (target / "vendored.py").write_text(_BAD_MODULE)
        (tmp_path / "mine.py").write_text("def fn():\n    return 1\n")
        assert cli_main([str(tmp_path)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_warn_only_flag_and_env(self, tmp_path, monkeypatch):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path), "--warn-only"]) == 0
        monkeypatch.setenv("LO_ANALYSIS_WARN", "1")
        assert cli_main([str(path)]) == 0
        # an explicit "off" value must keep enforcement ON — presence
        # alone is not consent to skip the gate
        for off in ("0", "false", "no", "off", " "):
            monkeypatch.setenv("LO_ANALYSIS_WARN", off)
            assert cli_main([str(path)]) == 1
        monkeypatch.delenv("LO_ANALYSIS_WARN")
        assert cli_main([str(path)]) == 1

    def test_non_utf8_file_is_a_finding_not_a_crash(
        self, tmp_path, capsys
    ):
        path = tmp_path / "latin.py"
        path.write_bytes(b"# caf\xe9\nx = 1\n")
        assert cli_main([str(path)]) == 1
        assert "LO000" in capsys.readouterr().out

    def test_unreadable_file_is_a_finding_not_a_crash(
        self, tmp_path, capsys, monkeypatch
    ):
        # a dangling symlink in the tree must name the file at fault
        # (and stay downgradable in warn-only mode), not traceback
        (tmp_path / "x.py").symlink_to(tmp_path / "gone.py")
        assert cli_main([str(tmp_path)]) == 1
        assert "LO000" in capsys.readouterr().out
        assert cli_main([str(tmp_path), "--warn-only"]) == 0


class TestBaselineWorkflow:
    def test_baseline_grandfathers_old_findings_only(
        self, tmp_path, capsys
    ):
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"

        assert (
            cli_main(
                [str(path), "--baseline", str(baseline), "--write-baseline"]
            )
            == 0
        )
        assert baseline.is_file()
        capsys.readouterr()

        # grandfathered finding no longer fails the build
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0
        assert "baselined" in capsys.readouterr().out

        # a NEW finding still fails, even with the baseline present
        path.write_text(
            _BAD_MODULE + "\ndef more(d):\n"
            "    _broadcast_json({'t': time.time()})\n"
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 1

    def test_baseline_matches_across_cwd_and_path_spelling(
        self, tmp_path, monkeypatch
    ):
        # keys are anchored to the baseline file's directory, so the
        # same baseline matches whether the analyzer ran from the repo
        # root (deploy preflight), from pytest's CWD with absolute
        # paths (the tier-1 gate), or anywhere else
        project = tmp_path / "project"
        project.mkdir()
        path = project / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = project / "baseline.txt"

        monkeypatch.chdir(project)
        assert (
            cli_main(["legacy.py", "--baseline", "baseline.txt",
                      "--write-baseline"])
            == 0
        )

        monkeypatch.chdir(tmp_path)
        assert (
            cli_main(["project/legacy.py", "--baseline", str(baseline)])
            == 0
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0

    def test_baseline_survives_line_shifts(self, tmp_path, capsys):
        # keys are line-number-free for EVERY rule — LO101 messages
        # must describe the guard by its expression, not its line
        path = tmp_path / "legacy.py"
        lo101 = (
            "import jax.numpy as jnp\n"
            "def handler(payload, coordinator):\n"
            "    if coordinator:\n"
            "        return jnp.sum(payload['x'])\n"
        )
        path.write_text(lo101)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline), "--write-baseline"])
        capsys.readouterr()

        # an unrelated edit shifts everything down two lines
        path.write_text("import os\nimport sys\n" + lo101)
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0
        assert "baselined" in capsys.readouterr().out

    def test_overlapping_paths_do_not_double_report(self, tmp_path):
        # a directory plus a file inside it must analyze the file
        # once, or the duplicate of a baselined finding reads as NEW
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline),
                  "--write-baseline"])
        assert (
            cli_main([str(tmp_path), str(path), "--baseline",
                      str(baseline)])
            == 0
        )

    def test_duplicate_of_baselined_pattern_is_new(self, tmp_path):
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline), "--write-baseline"])
        # a second identical occurrence consumes no baseline entry
        path.write_text(
            _BAD_MODULE
            + '\ndef run2(dispatcher):\n'
            '    dispatcher.submit("op", {"stamp": time.time()})\n'
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 1


class TestRuleMeta:
    """Meta-invariants over the rule registry and its documentation."""

    def test_every_rule_documented(self):
        """Every rule id — LO2xx included — appears in docs/analysis.md
        (the table a suppression comment points reviewers at)."""
        from learningorchestra_tpu.analysis.rules import RULES

        with open(
            os.path.join(_REPO_ROOT, "docs", "analysis.md"),
            encoding="utf-8",
        ) as handle:
            docs = handle.read()
        for rule_id in RULES:
            assert rule_id in docs, f"{rule_id} missing from docs/analysis.md"

    def test_every_rule_listed_by_cli(self, capsys):
        assert cli_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        from learningorchestra_tpu.analysis.rules import RULES

        for rule_id in RULES:
            assert rule_id in out

    def test_lo2xx_baseline_round_trip(self, tmp_path, capsys):
        """The baseline workflow holds for the concurrency family: a
        grandfathered LO2xx finding stops failing, a NEW instance of
        the same pattern still fails, and regenerating the baseline
        from a fixed tree leaves it empty."""
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_BY_RULE["LO203"])
        baseline = tmp_path / "baseline.txt"
        assert (
            cli_main(
                [str(path), "--baseline", str(baseline), "--write-baseline"]
            )
            == 0
        )
        capsys.readouterr()
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0
        assert "baselined" in capsys.readouterr().out

        # a second unguarded access is a NEW finding despite the baseline
        path.write_text(
            _BAD_BY_RULE["LO203"]
            + "    def peek(self, k):\n"
            "        return self._jobs.get(k)\n"
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 1

        # fix the file, regenerate: the baseline empties out — the
        # ISSUE 9 contract (findings get fixed, not grandfathered)
        path.write_text(
            "class M:\n"
            "    def put(self, k, v):\n"
            "        with self._lock:\n"
            "            self._jobs[k] = v\n"
            "    def wait(self, k):\n"
            "        with self._lock:\n"
            "            return self._jobs[k]\n"
        )
        assert (
            cli_main(
                [str(path), "--baseline", str(baseline), "--write-baseline"]
            )
            == 0
        )
        content = [
            line
            for line in baseline.read_text().splitlines()
            if line and not line.startswith("#")
        ]
        assert content == []


class TestChangedMode:
    """--changed: only findings new since the git merge-base fail."""

    @pytest.fixture()
    def repo(self, tmp_path, monkeypatch):
        def git(*args):
            subprocess.run(
                ["git", *args],
                cwd=tmp_path,
                check=True,
                capture_output=True,
                env={
                    **os.environ,
                    "GIT_AUTHOR_NAME": "t",
                    "GIT_AUTHOR_EMAIL": "t@t",
                    "GIT_COMMITTER_NAME": "t",
                    "GIT_COMMITTER_EMAIL": "t@t",
                },
            )

        git("init", "-b", "main")
        (tmp_path / "legacy.py").write_text(_BAD_MODULE)
        git("add", "-A")
        git("commit", "-m", "seed")
        git("checkout", "-b", "feature")
        monkeypatch.chdir(tmp_path)
        return tmp_path

    def test_preexisting_findings_pass_new_ones_fail(self, repo, capsys):
        # the merge-base's LO102 finding is grandfathered...
        assert cli_main(["--changed", "legacy.py"]) == 0
        assert "baselined" in capsys.readouterr().out
        # ...but a finding introduced on the branch fails
        (repo / "legacy.py").write_text(
            _BAD_MODULE
            + "\ndef more(dispatcher):\n"
            "    dispatcher.submit(\"op\", {\"t\": time.time()})\n"
        )
        assert cli_main(["--changed", "legacy.py"]) == 1

    def test_new_file_findings_all_fail(self, repo):
        (repo / "fresh.py").write_text(_BAD_MODULE)
        assert cli_main(["--changed", "fresh.py"]) == 1

    def test_fixed_file_is_clean(self, repo, capsys):
        (repo / "legacy.py").write_text("def fn():\n    return 1\n")
        assert cli_main(["--changed", "legacy.py"]) == 0
        assert "clean" in capsys.readouterr().out

    def test_explicit_ref(self, repo):
        assert cli_main(["--changed", "--base", "main", "legacy.py"]) == 0

    def test_base_without_changed_is_usage_error(self, repo, capsys):
        assert cli_main(["--base", "main", "legacy.py"]) == 2
        assert "--base" in capsys.readouterr().err

    def test_unknown_ref_is_usage_error(self, repo, capsys):
        assert cli_main(["--changed", "--base", "nope", "legacy.py"]) == 2
        assert "--changed" in capsys.readouterr().err

    def test_outside_git_repo_is_usage_error(
        self, tmp_path, monkeypatch, capsys
    ):
        outside = tmp_path / "plain"
        outside.mkdir()
        (outside / "x.py").write_text("pass\n")
        monkeypatch.chdir(outside)
        monkeypatch.setenv("GIT_CEILING_DIRECTORIES", str(tmp_path))
        assert cli_main(["--changed", "x.py"]) == 2
        assert "--changed" in capsys.readouterr().err

    def test_changed_with_baseline_refused(self, repo):
        (repo / "baseline.txt").write_text("")
        assert (
            cli_main(
                ["--changed", "--baseline", "baseline.txt", "legacy.py"]
            )
            == 2
        )
        assert (
            cli_main(["--changed", "--write-baseline", "legacy.py"]) == 2
        )


# --------------------------------------------------------------------
# LO301–LO306 — the deployment-contract family (project-level pass)
# --------------------------------------------------------------------


def _write_project(base) -> None:
    """A minimal-but-complete deployment-contract project: one knob
    validated explicitly in the run.sh heredoc (LO_GOOD_KNOB), one
    through a validator call (LO_TICK_S via conf.tick_s), a manifest
    map, one metric family, one fault point, and docs rows for all of
    it. ``project_findings`` over it is CLEAN; each rule's test breaks
    exactly one seam."""
    pkg = base / "learningorchestra_tpu"
    (pkg / "testing").mkdir(parents=True)
    (base / "deploy").mkdir()
    (base / "docs").mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "testing" / "__init__.py").write_text("")
    (pkg / "conf.py").write_text(
        textwrap.dedent(
            """\
            import os


            def _float_env(name, default):
                raw = os.environ.get(name, "").strip()
                return float(raw) if raw else default


            def tick_s():
                return _float_env("LO_TICK_S", 1.0)
            """
        )
    )
    (pkg / "mod.py").write_text(
        textwrap.dedent(
            """\
            import os


            def _int_env(name, default):
                raw = os.environ.get(name, "").strip()
                return int(raw) if raw else default


            def width():
                return _int_env("LO_GOOD_KNOB", 8)


            def declare(registry):
                registry.counter("lo_good_total")
            """
        )
    )
    (pkg / "testing" / "faults.py").write_text(
        textwrap.dedent(
            """\
            FAULT_POINTS = {
                "store.wire": "before a mutation applies",
            }
            """
        )
    )
    (base / "deploy" / "cluster.py").write_text(
        textwrap.dedent(
            """\
            SERVE_KNOBS = {
                "width": "LO_GOOD_KNOB",
            }
            """
        )
    )
    (base / "deploy" / "run.sh").write_text(
        textwrap.dedent(
            """\
            #!/usr/bin/env bash
            set -euo pipefail
            python - <<'EOF'
            import os
            from learningorchestra_tpu import conf

            value = os.environ.get("LO_GOOD_KNOB", "")
            if value and int(value) < 1:
                raise SystemExit("LO_GOOD_KNOB must be >= 1")
            conf.tick_s()
            EOF
            """
        )
    )
    (base / "docs" / "usage.md").write_text(
        textwrap.dedent(
            """\
            # Usage

            | env var | default | meaning |
            |---|---|---|
            | `LO_GOOD_KNOB` | `8` | worker width |
            | `LO_TICK_S` | `1.0` | monitor tick |
            """
        )
    )
    (base / "docs" / "observability.md").write_text(
        textwrap.dedent(
            """\
            # Observability

            | family | kind | meaning |
            |---|---|---|
            | `lo_good_total` | counter | good events |
            """
        )
    )
    (base / "docs" / "robustness.md").write_text(
        textwrap.dedent(
            """\
            # Robustness

            | point | env | where |
            |---|---|---|
            | `store.wire` | `LO_FAULT_STORE_WIRE` | before a mutation applies |
            """
        )
    )


def _project_findings(base, select=None):
    from learningorchestra_tpu.analysis.contracts import project_findings

    return project_findings(str(base), select)


def _append(path, text) -> None:
    with open(path, "a", encoding="utf-8") as handle:
        handle.write(textwrap.dedent(text))


class TestContractProjectPass:
    def test_clean_project_is_clean(self, tmp_path):
        _write_project(tmp_path)
        assert _project_findings(tmp_path) == []

    def test_non_project_dir_has_no_contract_pass(self, tmp_path):
        from learningorchestra_tpu.analysis.contracts import (
            find_project_root,
        )

        (tmp_path / "lone.py").write_text("def fn():\n    return 1\n")
        assert find_project_root(str(tmp_path / "lone.py")) is None

    def test_find_project_root_from_nested_path(self, tmp_path):
        from learningorchestra_tpu.analysis.contracts import (
            find_project_root,
        )

        _write_project(tmp_path)
        nested = tmp_path / "learningorchestra_tpu" / "mod.py"
        assert find_project_root(str(nested)) == str(tmp_path)


class TestLO301PreflightParity:
    def test_unvalidated_read_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def depth():
                return _int_env("LO_ORPHAN_KNOB", 2)
            """,
        )
        findings = _project_findings(tmp_path, {"LO301"})
        assert len(findings) == 1
        assert findings[0].rule == "LO301"
        assert "LO_ORPHAN_KNOB" in findings[0].message
        assert findings[0].path.endswith("mod.py")

    def test_dead_validation_flagged_at_run_sh(self, tmp_path):
        _write_project(tmp_path)
        run_sh = tmp_path / "deploy" / "run.sh"
        run_sh.write_text(
            run_sh.read_text().replace(
                "conf.tick_s()",
                'conf.tick_s()\nos.environ.get("LO_DEAD", "")',
            )
        )
        findings = _project_findings(tmp_path, {"LO301"})
        assert len(findings) == 1
        assert "LO_DEAD" in findings[0].message
        assert "dead validation" in findings[0].message
        assert findings[0].path.endswith("run.sh")

    def test_validator_call_counts_as_validation(self, tmp_path):
        # LO_TICK_S is validated only through conf.tick_s() in the
        # heredoc — the clean fixture proves call-resolution works
        _write_project(tmp_path)
        assert _project_findings(tmp_path, {"LO301"}) == []

    def test_allow_on_any_read_site_suppresses(self, tmp_path):
        _write_project(tmp_path)
        # two read sites: anchor lands in conf.py (sorts first), the
        # allow lives at the OTHER site in mod.py
        _append(
            tmp_path / "learningorchestra_tpu" / "conf.py",
            """
            def orphan_a():
                return _float_env("LO_ORPHAN_KNOB", 0.0)
            """,
        )
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def orphan_b():
                # lo: allow[LO301] test fixture justification
                return _int_env("LO_ORPHAN_KNOB", 2)
            """,
        )
        assert _project_findings(tmp_path, {"LO301"}) == []


class TestLO302ManifestParity:
    def test_unread_manifest_env_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "deploy" / "cluster.py",
            """
            STALE_KNOBS = {
                "stale": "LO_STALE",
            }
            """,
        )
        findings = _project_findings(tmp_path, {"LO302"})
        assert len(findings) == 1
        assert "LO_STALE" in findings[0].message
        assert findings[0].path.endswith("cluster.py")

    def test_allow_on_manifest_line_suppresses(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "deploy" / "cluster.py",
            """
            STALE_KNOBS = {
                "stale": "LO_STALE",  # lo: allow[LO302] staged rollout
            }
            """,
        )
        assert _project_findings(tmp_path, {"LO302"}) == []


class TestLO303MetricParity:
    def test_declared_but_undocumented_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def declare_more(registry):
                registry.gauge("lo_orphan_rows")
            """,
        )
        findings = _project_findings(tmp_path, {"LO303"})
        assert len(findings) == 1
        assert "lo_orphan_rows" in findings[0].message
        assert "gauge" in findings[0].message

    def test_documented_but_undeclared_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "docs" / "observability.md",
            "| `lo_ghost_total` | counter | gone |\n",
        )
        findings = _project_findings(tmp_path, {"LO303"})
        assert len(findings) == 1
        assert "lo_ghost_total" in findings[0].message
        assert findings[0].path.endswith("observability.md")

    def test_markdown_allow_comment_suppresses(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "docs" / "observability.md",
            "| `lo_ghost_total` | counter | gone |"
            " <!-- # lo: allow[LO303] retired family -->\n",
        )
        assert _project_findings(tmp_path, {"LO303"}) == []


class TestLO304FaultTableParity:
    def test_unregistered_docs_row_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "docs" / "robustness.md",
            "| `store.nope` | `LO_FAULT_STORE_NOPE` | nowhere |\n",
        )
        findings = _project_findings(tmp_path, {"LO304"})
        assert len(findings) == 1
        assert "LO_FAULT_STORE_NOPE" in findings[0].message

    def test_undocumented_fault_point_flagged(self, tmp_path):
        _write_project(tmp_path)
        faults = (
            tmp_path / "learningorchestra_tpu" / "testing" / "faults.py"
        )
        faults.write_text(
            faults.read_text().replace(
                '"store.wire": "before a mutation applies",',
                '"store.wire": "before a mutation applies",\n'
                '    "store.extra": "undocumented",',
            )
        )
        findings = _project_findings(tmp_path, {"LO304"})
        assert len(findings) == 1
        assert "store.extra" in findings[0].message
        assert findings[0].path.endswith("faults.py")

    def test_allow_on_fault_point_line_suppresses(self, tmp_path):
        _write_project(tmp_path)
        faults = (
            tmp_path / "learningorchestra_tpu" / "testing" / "faults.py"
        )
        faults.write_text(
            faults.read_text().replace(
                '"store.wire": "before a mutation applies",',
                '"store.wire": "before a mutation applies",\n'
                '    # lo: allow[LO304] docs row lands in the next PR\n'
                '    "store.extra": "undocumented",',
            )
        )
        assert _project_findings(tmp_path, {"LO304"}) == []


class TestLO305InlineEnvReads:
    def test_direct_read_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def inline():
                return os.environ.get("LO_GOOD_KNOB", "")
            """,
        )
        findings = _project_findings(tmp_path, {"LO305"})
        assert len(findings) == 1
        assert findings[0].rule == "LO305"
        assert "LO_GOOD_KNOB" in findings[0].message

    def test_helper_reads_not_flagged(self, tmp_path):
        _write_project(tmp_path)  # every fixture read is via *_env
        assert _project_findings(tmp_path, {"LO305"}) == []

    def test_config_module_exempt(self, tmp_path):
        _write_project(tmp_path)
        (tmp_path / "learningorchestra_tpu" / "config.py").write_text(
            "import os\n"
            "READ_ONCE = os.environ.get('LO_GOOD_KNOB', '')\n"
        )
        assert _project_findings(tmp_path, {"LO305"}) == []

    def test_deploy_launchers_exempt(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "deploy" / "cluster.py",
            """
            import os


            def launch():
                return os.environ.get("LO_GOOD_KNOB", "")
            """,
        )
        assert _project_findings(tmp_path, {"LO305"}) == []

    def test_validate_function_exempt(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def validate_width():
                return os.environ.get("LO_GOOD_KNOB", "")
            """,
        )
        assert _project_findings(tmp_path, {"LO305"}) == []

    def test_inline_allow_comment_suppresses(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def inline():
                # lo: allow[LO305] test fixture justification
                return os.environ.get("LO_GOOD_KNOB", "")
            """,
        )
        assert _project_findings(tmp_path, {"LO305"}) == []


class TestLO306DocsParity:
    def test_undocumented_knob_flagged(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def hidden():
                return _int_env("LO_UNDOC", 1)
            """,
        )
        findings = _project_findings(tmp_path, {"LO306"})
        assert len(findings) == 1
        assert "LO_UNDOC" in findings[0].message

    def test_fault_knobs_are_lo304s_domain(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def chaos():
                return os.environ.get("LO_FAULT_STORE_WIRE", "")
            """,
        )
        # documented per point (LO304), never per knob — and the read
        # is direct, so only LO305 would apply to the site
        assert _project_findings(tmp_path, {"LO306"}) == []
        assert _project_findings(tmp_path, {"LO301"}) == []

    def test_allow_at_read_site_suppresses(self, tmp_path):
        _write_project(tmp_path)
        _append(
            tmp_path / "learningorchestra_tpu" / "mod.py",
            """
            def hidden():
                # lo: allow[LO306] internal-only knob
                return _int_env("LO_UNDOC", 1)
            """,
        )
        assert _project_findings(tmp_path, {"LO306"}) == []


# rule id -> mutation of the clean synthetic project that must make
# the CLI fail with exactly that contract rule
_BREAK_BY_RULE = {
    "LO301": lambda base: _append(
        base / "learningorchestra_tpu" / "mod.py",
        "\ndef depth():\n    return _int_env('LO_ORPHAN_KNOB', 2)\n",
    ),
    "LO302": lambda base: _append(
        base / "deploy" / "cluster.py",
        "\nSTALE_KNOBS = {'stale': 'LO_STALE'}\n",
    ),
    "LO303": lambda base: _append(
        base / "docs" / "observability.md",
        "| `lo_ghost_total` | counter | gone |\n",
    ),
    "LO304": lambda base: _append(
        base / "docs" / "robustness.md",
        "| `store.nope` | `LO_FAULT_STORE_NOPE` | nowhere |\n",
    ),
    "LO305": lambda base: _append(
        base / "learningorchestra_tpu" / "mod.py",
        "\ndef inline():\n"
        "    return os.environ.get('LO_GOOD_KNOB', '')\n",
    ),
    "LO306": lambda base: _append(
        base / "learningorchestra_tpu" / "mod.py",
        "\ndef hidden():\n    return _int_env('LO_UNDOC', 1)\n",
    ),
}


class TestContractCli:
    @pytest.mark.parametrize("rule", sorted(_BREAK_BY_RULE))
    def test_each_contract_rule_fails_the_cli(
        self, rule, tmp_path, capsys
    ):
        _write_project(tmp_path)
        _BREAK_BY_RULE[rule](tmp_path)
        assert cli_main([str(tmp_path), "--select", rule]) == 1
        assert rule in capsys.readouterr().out

    def test_clean_project_through_cli(self, tmp_path, capsys):
        _write_project(tmp_path)
        assert cli_main([str(tmp_path)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_select_lo3_prefix_runs_the_family(self, tmp_path, capsys):
        _write_project(tmp_path)
        _BREAK_BY_RULE["LO306"](tmp_path)
        assert cli_main([str(tmp_path), "--select", "LO3"]) == 1
        assert "LO306" in capsys.readouterr().out

    def test_select_other_family_skips_project_pass(self, tmp_path):
        _write_project(tmp_path)
        _BREAK_BY_RULE["LO306"](tmp_path)
        assert cli_main([str(tmp_path), "--select", "LO101"]) == 0

    def test_broken_run_sh_surfaces_as_lo000(self, tmp_path, capsys):
        _write_project(tmp_path)
        (tmp_path / "deploy" / "run.sh").write_text(
            "#!/usr/bin/env bash\npython - <<'EOF'\ndef broken(:\nEOF\n"
        )
        assert cli_main([str(tmp_path)]) == 1
        assert "LO000" in capsys.readouterr().out

    def test_format_json_schema(self, tmp_path, capsys):
        _write_project(tmp_path)
        _BREAK_BY_RULE["LO306"](tmp_path)
        assert cli_main([str(tmp_path), "--format=json"]) == 1
        captured = capsys.readouterr()
        payload = json.loads(captured.out)
        # the undocumented knob is also unvalidated: LO301 rides along
        assert sorted(f["rule"] for f in payload) == ["LO301", "LO306"]
        for entry in payload:
            assert set(entry) == {
                "rule",
                "path",
                "line",
                "message",
                "suppressed",
            }
            assert entry["suppressed"] is False
        # the human summary moves to stderr so stdout parses whole
        assert "finding" in captured.err

    def test_format_json_clean_is_empty_array(self, tmp_path, capsys):
        _write_project(tmp_path)
        assert cli_main([str(tmp_path), "--format=json"]) == 0
        captured = capsys.readouterr()
        assert json.loads(captured.out) == []
        assert "clean" in captured.err

    def test_contract_baseline_round_trip(self, tmp_path, capsys):
        """Grandfather a contract finding, see it baselined (and
        marked suppressed in json), fix it, regenerate empty."""
        _write_project(tmp_path)
        _BREAK_BY_RULE["LO302"](tmp_path)
        baseline = tmp_path / "baseline.txt"
        assert (
            cli_main(
                [str(tmp_path), "--write-baseline", "--baseline",
                 str(baseline)]
            )
            == 0
        )
        capsys.readouterr()
        assert (
            cli_main([str(tmp_path), "--baseline", str(baseline)]) == 0
        )
        assert "baselined" in capsys.readouterr().out
        assert (
            cli_main(
                [str(tmp_path), "--baseline", str(baseline),
                 "--format=json"]
            )
            == 0
        )
        payload = json.loads(capsys.readouterr().out)
        assert [f["suppressed"] for f in payload] == [True]
        # fix the drift; the regenerated baseline ends EMPTY — the
        # shipped tree's contract (ISSUE 16: end-empty sweep)
        (tmp_path / "deploy" / "cluster.py").write_text(
            "SERVE_KNOBS = {\n    'width': 'LO_GOOD_KNOB',\n}\n"
        )
        assert (
            cli_main(
                [str(tmp_path), "--write-baseline", "--baseline",
                 str(baseline)]
            )
            == 0
        )
        body = [
            line
            for line in baseline.read_text().splitlines()
            if line.strip() and not line.startswith("#")
        ]
        assert body == []


class TestContractChangedMode:
    def test_merge_base_contract_findings_grandfathered(
        self, tmp_path, monkeypatch, capsys
    ):
        def git(*args):
            subprocess.run(
                ["git", *args],
                cwd=tmp_path,
                check=True,
                capture_output=True,
                env={
                    **os.environ,
                    "GIT_AUTHOR_NAME": "t",
                    "GIT_AUTHOR_EMAIL": "t@t",
                    "GIT_COMMITTER_NAME": "t",
                    "GIT_COMMITTER_EMAIL": "t@t",
                },
            )

        _write_project(tmp_path)
        _BREAK_BY_RULE["LO306"](tmp_path)  # pre-existing drift
        git("init", "-b", "main")
        git("add", "-A")
        git("commit", "-m", "seed")
        git("checkout", "-b", "feature")
        monkeypatch.chdir(tmp_path)
        # the merge-base's contract finding is grandfathered...
        assert cli_main(["--changed", "."]) == 0
        assert "baselined" in capsys.readouterr().out
        # ...but NEW contract drift on the branch fails
        _BREAK_BY_RULE["LO302"](tmp_path)
        assert cli_main(["--changed", "."]) == 1
        out = capsys.readouterr().out
        assert "LO302" in out


class TestContractRegistryAntiRot:
    """The registry must keep extracting ALL of the real tree's
    artifacts — a refactor that silently breaks one extraction would
    make the parity rules vacuously pass."""

    def test_every_registry_section_nonempty_on_real_tree(self):
        from learningorchestra_tpu.analysis.registry import build_registry

        registry = build_registry(_REPO_ROOT)
        assert registry.problems == []
        assert registry.run_sh == "deploy/run.sh"
        for section in (
            "env_reads",
            "validated_explicit",
            "validated_resolved",
            "manifest_knobs",
            "metrics",
            "doc_metrics",
            "doc_knobs",
            "doc_faults",
            "fault_points",
        ):
            assert getattr(registry, section), f"{section} extracted empty"
        # the scale the rules police — not one token fixture each
        assert len(registry.env_reads) >= 40
        assert len(registry.validated) >= 40
        assert len(registry.metrics) >= 50
        assert len(registry.doc_knobs) >= 40
        assert len(registry.fault_points) >= 8

    def test_static_metrics_match_docs_both_ways(self):
        from learningorchestra_tpu.analysis.registry import build_registry

        registry = build_registry(_REPO_ROOT)
        assert set(registry.metrics) == set(registry.doc_metrics)

    def test_declared_families_snapshot(self):
        """The MetricsRegistry introspection hook LO303's anti-rot
        story leans on: name -> kind for every declared family."""
        from learningorchestra_tpu.telemetry.metrics import MetricsRegistry

        registry = MetricsRegistry()
        registry.counter("lo_x_total", "x events")
        registry.gauge("lo_y_rows", "y rows")
        registry.histogram("lo_z_seconds", "z latency")
        assert registry.declared_families() == {
            "lo_x_total": "counter",
            "lo_y_rows": "gauge",
            "lo_z_seconds": "histogram",
        }

    def test_live_declarations_visible_to_static_extraction(self):
        """Families declared through the live registry by an imported
        module must be names the static extraction also found — the
        two views of 'declared' cannot drift."""
        from learningorchestra_tpu.analysis.registry import build_registry
        from learningorchestra_tpu.telemetry import metrics as _metrics
        from learningorchestra_tpu.testing import faults  # noqa: F401 declares lo_fault_*

        registry = build_registry(_REPO_ROOT)
        live = _metrics.global_registry().declared_families()
        lo_families = {
            name for name in live if name.startswith("lo_")
        }
        missing = lo_families - set(registry.metrics)
        assert not missing, (
            f"live-declared families invisible to the registry: {missing}"
        )


def _copy_real_tree(tmp_path):
    target = tmp_path / "tree"
    target.mkdir()
    for part in ("learningorchestra_tpu", "deploy", "docs"):
        shutil.copytree(
            os.path.join(_REPO_ROOT, part),
            target / part,
            ignore=shutil.ignore_patterns("__pycache__"),
        )
    return target


class TestContractMutationsOnRealTree:
    """ISSUE 16 acceptance: seeded mutations of the REAL artifacts
    each produce exactly the expected new finding — proof the rules
    police the real deployment surface, not just synthetic fixtures."""

    def test_real_tree_is_clean(self, tmp_path):
        tree = _copy_real_tree(tmp_path)
        assert _project_findings(tree) == []

    def test_deleting_a_run_sh_validation_fires_lo301(self, tmp_path):
        tree = _copy_real_tree(tmp_path)
        run_sh = tree / "deploy" / "run.sh"
        text = run_sh.read_text()
        assert '"LO_STORE_COMPRESS",' in text
        run_sh.write_text(text.replace('"LO_STORE_COMPRESS",', "", 1))
        findings = _project_findings(tree)
        assert [f.rule for f in findings] == ["LO301"]
        assert "LO_STORE_COMPRESS" in findings[0].message

    def test_deleting_a_metric_row_fires_lo303(self, tmp_path):
        tree = _copy_real_tree(tmp_path)
        doc = tree / "docs" / "observability.md"
        lines = doc.read_text().splitlines(keepends=True)
        victim = victim_name = None
        for index, line in enumerate(lines):
            match = re.match(r"\|\s*`(lo_[a-z0-9_]+)`\s*\|", line)
            if match and "` / `" not in line:
                victim, victim_name = index, match.group(1)
                break
        assert victim is not None, "no single-family metric row found"
        del lines[victim]
        doc.write_text("".join(lines))
        findings = _project_findings(tree)
        assert [f.rule for f in findings] == ["LO303"]
        assert victim_name in findings[0].message

    def test_deleting_a_docs_knob_row_fires_lo306(self, tmp_path):
        tree = _copy_real_tree(tmp_path)
        doc = tree / "docs" / "dataplane.md"
        lines = doc.read_text().splitlines(keepends=True)
        keep = [
            line
            for line in lines
            if not line.startswith("| `LO_WIRE_ROWS` ")
        ]
        assert len(keep) == len(lines) - 1
        doc.write_text("".join(keep))
        findings = _project_findings(tree)
        assert [f.rule for f in findings] == ["LO306"]
        assert "LO_WIRE_ROWS" in findings[0].message

    def test_shipped_tree_carries_no_baseline_file(self):
        """The sweep ended EMPTY: every LO3xx finding was fixed or
        carries a justified in-place allow — no grandfathered
        backlog."""
        assert not os.path.exists(
            os.path.join(_REPO_ROOT, "analysis-baseline.txt")
        )


# --------------------------------------------------------------------
# the gate: the shipped tree must be clean
# --------------------------------------------------------------------


class TestRepoGate:
    def test_framework_tree_has_no_findings(self, capsys):
        """Zero non-baselined findings over every shipped source tree —
        the PR gate. New intentional violations need an inline
        ``# lo: allow[LOxxx]`` with a justifying comment."""
        paths = [
            os.path.join(_REPO_ROOT, "learningorchestra_tpu"),
            os.path.join(_REPO_ROOT, "learning_orchestra_client"),
            os.path.join(_REPO_ROOT, "deploy"),
        ]
        exit_code = cli_main(paths)
        output = capsys.readouterr().out
        assert exit_code == 0, f"SPMD-safety findings:\n{output}"

    def test_module_cli_entry_point(self):
        """The documented invocation: ``python -m
        learningorchestra_tpu.analysis learningorchestra_tpu``."""
        result = subprocess.run(
            [
                sys.executable,
                "-m",
                "learningorchestra_tpu.analysis",
                "learningorchestra_tpu",
            ],
            capture_output=True,
            text=True,
            cwd=_REPO_ROOT,
            timeout=120,
        )
        assert result.returncode == 0, result.stdout + result.stderr
