"""SPMD-safety analyzer: per-rule fixtures, CLI contract, repo gate.

Every rule family (LO101–LO104) gets at least one positive (bad code
the rule must flag) and one negative (the nearby good idiom it must NOT
flag) fixture. The gate at the bottom runs the analyzer over the real
source trees and asserts zero non-baselined findings — the invariant
the tentpole exists to enforce on every PR.
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

import pytest

from learningorchestra_tpu.analysis import analyze_source
from learningorchestra_tpu.analysis.cli import main as cli_main

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings_for(source: str, select=None):
    return analyze_source(textwrap.dedent(source), "probe.py", select)


def rules_of(source: str) -> set:
    return {finding.rule for finding in findings_for(source)}


# --------------------------------------------------------------------
# LO101 — collective divergence
# --------------------------------------------------------------------


class TestLO101CollectiveDivergence:
    def test_jnp_dispatch_under_coordinator_guard(self):
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator):
                if coordinator:
                    return jnp.sum(payload["x"])
        """
        assert "LO101" in rules_of(src)

    def test_collective_under_write_outputs_guard(self):
        src = """
            def handler(model, write_outputs):
                if write_outputs:
                    gathered = gather_model(model)
        """
        assert "LO101" in rules_of(src)

    def test_early_return_guard_poisons_rest_of_function(self):
        # `if process_index() != 0: return` makes everything after it
        # coordinator-only — the deadlock shape without any indentation
        src = """
            import jax

            def handler(model, payload):
                if jax.process_index() != 0:
                    return
                model.fit(payload)
        """
        assert "LO101" in rules_of(src)

    def test_else_branch_is_equally_divergent(self):
        src = """
            def handler(dispatcher, payload, coordinator):
                if coordinator:
                    pass
                else:
                    dispatcher.submit("op", payload)
        """
        assert "LO101" in rules_of(src)

    def test_host_writes_under_guard_are_fine(self):
        src = """
            def handler(store, metadata, write_outputs):
                if write_outputs:
                    store.insert_one("out", metadata)
        """
        assert rules_of(src) == set()

    def test_collective_outside_guard_is_fine(self):
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator):
                total = jnp.sum(payload["x"])
                if coordinator:
                    print(total)
        """
        assert rules_of(src) == set()

    def test_process_count_is_not_a_divergence_guard(self):
        # process_count is identical on every process — `if
        # jax.process_count() == 1` selects a MODE, not a subset of
        # processes
        src = """
            import jax
            import jax.numpy as jnp

            def handler(payload):
                if jax.process_count() == 1:
                    return jnp.sum(payload["x"])
        """
        assert rules_of(src) == set()

    def test_def_under_guard_not_flagged(self):
        # a closure defined under a guard runs on its own schedule
        src = """
            import jax

            def start(submit):
                if jax.process_index() != 0:
                    return

                def beat():
                    return _broadcast_json({"op": "ping"})
                return beat
        """
        assert rules_of(src) == set()

    def test_while_loop_guard_is_divergent(self):
        # a coordinator-only polling loop is the same deadlock shape
        # as an if-guard, without the if
        src = """
            import jax

            def poll(dispatcher, payload):
                while jax.process_index() == 0:
                    dispatcher.submit("op", payload)
        """
        assert "LO101" in rules_of(src)

    def test_while_else_runs_on_every_process(self):
        src = """
            def run(coordinator, log):
                while coordinator:
                    log.flush()
                else:
                    _broadcast_json({"op": "sync"})
        """
        assert rules_of(src) == set()

    def test_conditional_expression_guard_is_divergent(self):
        src = """
            def run(model, coordinator):
                gathered = gather_model(model) if coordinator else None
                return gathered
        """
        assert "LO101" in rules_of(src)

    def test_short_circuit_and_guard_is_divergent(self):
        # `coordinator and gather(...)`: short-circuiting makes the
        # collective coordinator-only with no if statement at all
        src = """
            def run(model, coordinator):
                ok = coordinator and gather_model(model)
                return ok
        """
        assert "LO101" in rules_of(src)

    def test_short_circuit_collective_before_guard_is_fine(self):
        # evaluation order matters: the collective runs on EVERY
        # process here, the divergent name only gates the result
        src = """
            def run(model, coordinator):
                ok = gather_model(model) and coordinator
                return ok
        """
        assert rules_of(src) == set()

    def test_nested_guards_report_once(self):
        # guards nesting through a non-If compound statement must not
        # double-count one defect (one finding, two baseline entries)
        src = """
            import jax.numpy as jnp

            def handler(payload, coordinator, write_outputs, lock):
                if coordinator:
                    with lock:
                        if write_outputs:
                            return jnp.sum(payload["x"])
        """
        found = [f for f in findings_for(src) if f.rule == "LO101"]
        assert len(found) == 1

    def test_inline_allow_comment_suppresses(self):
        src = """
            def shutdown(coordinator):
                if coordinator:
                    _broadcast_json({"op": "x"})  # lo: allow[LO101]
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO102 — broadcast determinism
# --------------------------------------------------------------------


class TestLO102BroadcastDeterminism:
    def test_wall_clock_through_assignment_into_submit(self):
        # the shape of the ml/builder.py trace-dir bug (this rule's
        # motivating example): a wall-clock value laundered through an
        # f-string and a dict before reaching the payload
        src = """
            import time

            def run(dispatcher):
                stamp = int(time.time() * 1000)
                payload = {"dir": f"build_{stamp}"}
                dispatcher.submit("build_model", payload)
        """
        assert "LO102" in rules_of(src)

    def test_unseeded_random_direct_into_broadcast(self):
        src = """
            import random

            def run():
                _broadcast_json({"seed": random.random()})
        """
        assert "LO102" in rules_of(src)

    def test_unseeded_default_rng_flagged(self):
        src = """
            import numpy as np

            def run():
                _broadcast_json({"draw": np.random.default_rng().random()})
        """
        assert "LO102" in rules_of(src)

    def test_assigned_unseeded_rng_flagged_through_method_call(self):
        # the common spelling: construct once, draw later — receiver
        # taint must ride through the method call
        src = """
            import numpy as np

            def run():
                rng = np.random.default_rng()
                _broadcast_json({"draw": rng.random()})
        """
        assert "LO102" in rules_of(src)

    def test_set_iteration_order_flagged(self):
        src = """
            def run(names):
                _broadcast_json(list(set(names)))
        """
        assert "LO102" in rules_of(src)

    def test_tuple_assignment_carries_taint(self):
        # the motivating bug spelled as a tuple assign must not slip
        # through the single-Name fast path
        src = """
            import time

            def run(dispatcher):
                stamp, other = time.time(), 1
                dispatcher.submit("op", {"t": stamp})
        """
        assert "LO102" in rules_of(src)

    def test_tuple_assignment_untainted_element_is_fine(self):
        src = """
            import time

            def run(dispatcher):
                stamp, other = time.time(), 1
                dispatcher.submit("op", {"n": other})
        """
        assert rules_of(src) == set()

    def test_unpacking_single_tainted_value_taints_all_names(self):
        src = """
            import time

            def run(dispatcher):
                minutes, seconds = divmod(time.time(), 60)
                _broadcast_json({"s": seconds})
        """
        assert "LO102" in rules_of(src)

    def test_for_tuple_target_carries_set_iteration_taint(self):
        src = """
            def run(pairs):
                for key, value in set(pairs):
                    _broadcast_json({"k": key})
        """
        assert "LO102" in rules_of(src)

    def test_rebind_inside_branch_clears_taint_before_sink(self):
        # the sink sees the env AFTER the branch's own rebind — a
        # false positive here would hard-fail the deploy preflight on
        # correct code
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                    _broadcast_json({"op": x})
        """
        assert rules_of(src) == set()

    def test_sink_after_branch_still_sees_outer_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    pass
                _broadcast_json({"op": x})
        """
        assert "LO102" in rules_of(src)

    def test_taint_from_one_branch_survives_the_join(self):
        # conditionally tainted IS tainted: one process takes the
        # clock branch, another doesn't — the payloads diverge
        src = """
            import time

            def run(cond):
                if cond:
                    x = time.time()
                else:
                    x = 1
                _broadcast_json({"t": x})
        """
        assert "LO102" in rules_of(src)

    def test_branch_rebind_does_not_erase_fallthrough_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                _broadcast_json({"t": x})
        """
        assert "LO102" in rules_of(src)

    def test_rebind_on_every_path_clears_taint(self):
        src = """
            import time

            def run(cond):
                x = time.time()
                if cond:
                    x = 1
                else:
                    x = 2
                _broadcast_json({"t": x})
        """
        assert rules_of(src) == set()

    def test_sorted_set_is_deterministic(self):
        src = """
            def run(names):
                _broadcast_json(sorted(set(names)))
        """
        assert rules_of(src) == set()

    def test_seeded_rng_is_fine(self):
        src = """
            import numpy as np

            def run(seed):
                rng = np.random.default_rng(seed)
                _broadcast_json({"draw": float(rng.random())})
        """
        assert rules_of(src) == set()

    def test_clock_used_locally_is_fine(self):
        src = """
            import time

            def run(dispatcher, payload):
                start = time.time()
                dispatcher.submit("op", payload)
                return time.time() - start
        """
        assert rules_of(src) == set()

    def test_non_dispatcher_submit_not_a_sink(self):
        src = """
            import time

            def run(pool, fit):
                pool.submit(fit, time.time())
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO103 — trace safety
# --------------------------------------------------------------------


class TestLO103TraceSafety:
    def test_float_on_traced_value_in_jit(self):
        src = """
            import jax

            @jax.jit
            def fn(x):
                return float(x.sum())
        """
        assert "LO103" in rules_of(src)

    def test_item_and_print_in_partial_jit(self):
        src = """
            import jax
            from functools import partial

            @partial(jax.jit, static_argnames=("n",))
            def fn(x, n):
                print(x)
                return x.item()
        """
        findings = findings_for(src)
        assert sum(f.rule == "LO103" for f in findings) == 2

    def test_numpy_call_in_jit_wrapped_function(self):
        src = """
            import jax
            import numpy as np

            def fn(x):
                return np.asarray(x)

            fast = jax.jit(fn)
        """
        assert "LO103" in rules_of(src)

    def test_nested_def_inside_jit_is_traced_too(self):
        src = """
            import jax

            @jax.jit
            def outer(x):
                def inner(v):
                    return float(v)
                return inner(x)
        """
        assert "LO103" in rules_of(src)

    def test_static_shape_math_is_fine(self):
        src = """
            import jax

            @jax.jit
            def fn(x):
                n = int(x.shape[0] * 2)
                m = float(len(x.shape))
                return x.reshape(n // 2, -1) * m
        """
        assert rules_of(src) == set()

    def test_same_calls_outside_jit_are_fine(self):
        src = """
            import numpy as np

            def host_fn(x):
                print(x)
                return float(np.asarray(x).sum())
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# LO104 — dtype hygiene
# --------------------------------------------------------------------


class TestLO104DtypeHygiene:
    def test_np_float64_in_jit(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def fn(x):
                return x.astype(np.float64)
        """
        assert "LO104" in rules_of(src)

    def test_float64_string_dtype_in_jit(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fn(n):
                return jnp.zeros(3, dtype="float64")
        """
        assert "LO104" in rules_of(src)

    def test_jnp_float64_dtype_outside_jit(self):
        # op-by-op dispatch is device code even without @jit
        src = """
            import jax.numpy as jnp
            import numpy as np

            def fn(values):
                return jnp.asarray(values, dtype=np.float64)
        """
        assert "LO104" in rules_of(src)

    def test_host_side_float64_is_fine(self):
        # the store's column format IS float64 — host paths are exempt
        src = """
            import numpy as np

            def to_column(values):
                return np.asarray(values, dtype=np.float64)
        """
        assert rules_of(src) == set()

    def test_default_dtypes_in_jit_are_fine(self):
        src = """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def fn(x):
                return jnp.zeros_like(x) + jnp.float32(1.0)
        """
        assert rules_of(src) == set()


# --------------------------------------------------------------------
# CLI contract + baseline workflow
# --------------------------------------------------------------------

_BAD_MODULE = """\
import time

def run(dispatcher):
    dispatcher.submit("op", {"stamp": time.time()})
"""


_BAD_BY_RULE = {
    "LO101": (
        "import jax.numpy as jnp\n"
        "def handler(payload, coordinator):\n"
        "    if coordinator:\n"
        "        return jnp.sum(payload['x'])\n"
    ),
    "LO102": _BAD_MODULE,
    "LO103": (
        "import jax\n"
        "@jax.jit\n"
        "def fn(x):\n"
        "    return float(x.sum())\n"
    ),
    "LO104": (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def fn(v):\n"
        "    return jnp.asarray(v, dtype=np.float64)\n"
    ),
}


class TestCli:
    @pytest.mark.parametrize("rule", sorted(_BAD_BY_RULE))
    def test_each_rule_family_fails_the_cli(self, rule, tmp_path, capsys):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_BY_RULE[rule])
        assert cli_main([str(path)]) == 1
        assert rule in capsys.readouterr().out

    def test_clean_file_exits_zero(self, tmp_path, capsys):
        path = tmp_path / "clean.py"
        path.write_text("def fn():\n    return 1\n")
        assert cli_main([str(path)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_findings_exit_nonzero_with_location_format(
        self, tmp_path, capsys
    ):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path)]) == 1
        out = capsys.readouterr().out
        assert ":4: LO102 " in out  # file:line: LOxxx message

    def test_syntax_error_reported_not_crashed(self, tmp_path, capsys):
        path = tmp_path / "broken.py"
        path.write_text("def fn(:\n")
        assert cli_main([str(path)]) == 1
        assert "LO000" in capsys.readouterr().out

    def test_select_filters_rules(self, tmp_path):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path), "--select", "LO103"]) == 0
        assert cli_main([str(path), "--select", "LO102"]) == 1

    def test_unknown_rule_and_missing_path_are_usage_errors(self, tmp_path):
        path = tmp_path / "x.py"
        path.write_text("pass\n")
        assert cli_main([str(path), "--select", "LO999"]) == 2
        assert cli_main([str(tmp_path / "missing.py")]) == 2

    def test_select_with_trailing_comma_stays_filtered(self, tmp_path):
        # "LO103, " must not smuggle in an empty token that
        # prefix-matches every rule
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)  # violates LO102 only
        assert cli_main([str(path), "--select", "LO103, "]) == 0
        assert cli_main([str(path), "--select", " , "]) == 2

    def test_explicit_file_without_py_suffix_is_analyzed(
        self, tmp_path, capsys
    ):
        # a green run that silently skipped the named file would be
        # worse than a usage error
        path = tmp_path / "job_script"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path)]) == 1
        assert "LO102" in capsys.readouterr().out

    def test_write_baseline_with_select_is_refused(self, tmp_path):
        # a filtered write would truncate other rules' grandfathered
        # entries and break the next full preflight
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        assert (
            cli_main(
                [str(path), "--baseline", str(baseline),
                 "--write-baseline", "--select", "LO101"]
            )
            == 2
        )
        assert not baseline.exists()

    def test_missing_explicit_baseline_is_a_usage_error(self, tmp_path):
        path = tmp_path / "x.py"
        path.write_text("pass\n")
        assert (
            cli_main([str(path), "--baseline", str(tmp_path / "nope.txt")])
            == 2
        )
        # --write-baseline CREATES the file, so a missing path is fine
        assert (
            cli_main(
                [str(path), "--baseline", str(tmp_path / "new.txt"),
                 "--write-baseline"]
            )
            == 0
        )

    def test_directory_walk_skips_hidden_and_vendored_dirs(
        self, tmp_path, capsys
    ):
        # .venv / build / *.egg-info under an analyzed directory are
        # third-party or generated code the gate must not lint
        for vendored in (".venv/site-packages", "build", "pkg.egg-info"):
            target = tmp_path / vendored
            target.mkdir(parents=True)
            (target / "vendored.py").write_text(_BAD_MODULE)
        (tmp_path / "mine.py").write_text("def fn():\n    return 1\n")
        assert cli_main([str(tmp_path)]) == 0
        assert "clean" in capsys.readouterr().out

    def test_warn_only_flag_and_env(self, tmp_path, monkeypatch):
        path = tmp_path / "bad.py"
        path.write_text(_BAD_MODULE)
        assert cli_main([str(path), "--warn-only"]) == 0
        monkeypatch.setenv("LO_ANALYSIS_WARN", "1")
        assert cli_main([str(path)]) == 0
        # an explicit "off" value must keep enforcement ON — presence
        # alone is not consent to skip the gate
        for off in ("0", "false", "no", "off", " "):
            monkeypatch.setenv("LO_ANALYSIS_WARN", off)
            assert cli_main([str(path)]) == 1
        monkeypatch.delenv("LO_ANALYSIS_WARN")
        assert cli_main([str(path)]) == 1

    def test_non_utf8_file_is_a_finding_not_a_crash(
        self, tmp_path, capsys
    ):
        path = tmp_path / "latin.py"
        path.write_bytes(b"# caf\xe9\nx = 1\n")
        assert cli_main([str(path)]) == 1
        assert "LO000" in capsys.readouterr().out

    def test_unreadable_file_is_a_finding_not_a_crash(
        self, tmp_path, capsys, monkeypatch
    ):
        # a dangling symlink in the tree must name the file at fault
        # (and stay downgradable in warn-only mode), not traceback
        (tmp_path / "x.py").symlink_to(tmp_path / "gone.py")
        assert cli_main([str(tmp_path)]) == 1
        assert "LO000" in capsys.readouterr().out
        assert cli_main([str(tmp_path), "--warn-only"]) == 0


class TestBaselineWorkflow:
    def test_baseline_grandfathers_old_findings_only(
        self, tmp_path, capsys
    ):
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"

        assert (
            cli_main(
                [str(path), "--baseline", str(baseline), "--write-baseline"]
            )
            == 0
        )
        assert baseline.is_file()
        capsys.readouterr()

        # grandfathered finding no longer fails the build
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0
        assert "baselined" in capsys.readouterr().out

        # a NEW finding still fails, even with the baseline present
        path.write_text(
            _BAD_MODULE + "\ndef more(d):\n"
            "    _broadcast_json({'t': time.time()})\n"
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 1

    def test_baseline_matches_across_cwd_and_path_spelling(
        self, tmp_path, monkeypatch
    ):
        # keys are anchored to the baseline file's directory, so the
        # same baseline matches whether the analyzer ran from the repo
        # root (deploy preflight), from pytest's CWD with absolute
        # paths (the tier-1 gate), or anywhere else
        project = tmp_path / "project"
        project.mkdir()
        path = project / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = project / "baseline.txt"

        monkeypatch.chdir(project)
        assert (
            cli_main(["legacy.py", "--baseline", "baseline.txt",
                      "--write-baseline"])
            == 0
        )

        monkeypatch.chdir(tmp_path)
        assert (
            cli_main(["project/legacy.py", "--baseline", str(baseline)])
            == 0
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0

    def test_baseline_survives_line_shifts(self, tmp_path, capsys):
        # keys are line-number-free for EVERY rule — LO101 messages
        # must describe the guard by its expression, not its line
        path = tmp_path / "legacy.py"
        lo101 = (
            "import jax.numpy as jnp\n"
            "def handler(payload, coordinator):\n"
            "    if coordinator:\n"
            "        return jnp.sum(payload['x'])\n"
        )
        path.write_text(lo101)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline), "--write-baseline"])
        capsys.readouterr()

        # an unrelated edit shifts everything down two lines
        path.write_text("import os\nimport sys\n" + lo101)
        assert cli_main([str(path), "--baseline", str(baseline)]) == 0
        assert "baselined" in capsys.readouterr().out

    def test_overlapping_paths_do_not_double_report(self, tmp_path):
        # a directory plus a file inside it must analyze the file
        # once, or the duplicate of a baselined finding reads as NEW
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline),
                  "--write-baseline"])
        assert (
            cli_main([str(tmp_path), str(path), "--baseline",
                      str(baseline)])
            == 0
        )

    def test_duplicate_of_baselined_pattern_is_new(self, tmp_path):
        path = tmp_path / "legacy.py"
        path.write_text(_BAD_MODULE)
        baseline = tmp_path / "baseline.txt"
        cli_main([str(path), "--baseline", str(baseline), "--write-baseline"])
        # a second identical occurrence consumes no baseline entry
        path.write_text(
            _BAD_MODULE
            + '\ndef run2(dispatcher):\n'
            '    dispatcher.submit("op", {"stamp": time.time()})\n'
        )
        assert cli_main([str(path), "--baseline", str(baseline)]) == 1


# --------------------------------------------------------------------
# the gate: the shipped tree must be clean
# --------------------------------------------------------------------


class TestRepoGate:
    def test_framework_tree_has_no_findings(self, capsys):
        """Zero non-baselined findings over every shipped source tree —
        the PR gate. New intentional violations need an inline
        ``# lo: allow[LOxxx]`` with a justifying comment."""
        paths = [
            os.path.join(_REPO_ROOT, "learningorchestra_tpu"),
            os.path.join(_REPO_ROOT, "learning_orchestra_client"),
            os.path.join(_REPO_ROOT, "deploy"),
        ]
        exit_code = cli_main(paths)
        output = capsys.readouterr().out
        assert exit_code == 0, f"SPMD-safety findings:\n{output}"

    def test_module_cli_entry_point(self):
        """The documented invocation: ``python -m
        learningorchestra_tpu.analysis learningorchestra_tpu``."""
        result = subprocess.run(
            [
                sys.executable,
                "-m",
                "learningorchestra_tpu.analysis",
                "learningorchestra_tpu",
            ],
            capture_output=True,
            text=True,
            cwd=_REPO_ROOT,
            timeout=120,
        )
        assert result.returncode == 0, result.stdout + result.stderr
