"""Horizontal store sharding (docs/dataplane.md): stripe placement
arithmetic, scatter-gather parity against the unsharded store — in
memory and over the wire — the shard-map topology contract, the
degenerate single-group mode's byte-identical wire traffic, journal
scope suffixing, and the kill-one-shard-primary chaos drill (fast
in-process variant here; the slow subprocess variant rides the same
file under ``@pytest.mark.slow``)."""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
import requests

from learningorchestra_tpu.core import shardmap
from learningorchestra_tpu.core.columns import Column
from learningorchestra_tpu.core.shardmap import ShardLayout
from learningorchestra_tpu.core.shardstore import ShardedStore
from learningorchestra_tpu.core.store import ROW_ID, InMemoryStore
from learningorchestra_tpu.core.store_service import (
    RemoteStore,
    connect,
    create_store_app,
    serve,
)
from learningorchestra_tpu.sched import shard_scope
from learningorchestra_tpu.utils.web import ServerThread

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(predicate, timeout=15.0, message="condition", tick=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(tick)
    raise AssertionError(f"timed out waiting for {message}")


class TestShardLayout:
    def test_stripe_arithmetic_and_local_contiguity(self):
        layout = ShardLayout(4, 8)
        assert layout.stripe_of(1) == 0
        assert layout.stripe_of(8) == 0
        assert layout.stripe_of(9) == 1
        with pytest.raises(ValueError):
            layout.stripe_of(0)
        # within a stripe every id maps to the SAME shard and local ids
        # are consecutive — the contiguity the store's dense-append
        # contract needs
        for stripe in range(40):
            base = stripe * 8 + 1
            placements = [layout.global_to_local(base + k) for k in range(8)]
            shards = {shard for shard, _ in placements}
            assert len(shards) == 1
            locals_ = [local for _, local in placements]
            assert locals_ == list(range(locals_[0], locals_[0] + 8))

    def test_roundtrip_global_local(self):
        layout = ShardLayout(3, 8)
        for gid in range(1, 500):
            shard, local = layout.global_to_local(gid)
            assert layout.local_to_global(shard, local) == gid
            assert layout.shard_of_id(gid) == shard

    def test_single_shard_is_identity(self):
        layout = ShardLayout(1, 8192)
        for gid in (1, 2, 8192, 8193, 10**9):
            assert layout.global_to_local(gid) == (0, gid)
            assert layout.local_to_global(0, gid) == gid

    def test_decompose_covers_range_one_run_per_shard(self):
        layout = ShardLayout(4, 8)
        runs = layout.decompose(1, 1000)
        assert sum(run["rows"] for run in runs) == 1000
        assert [run["shard"] for run in runs] == sorted(
            {run["shard"] for run in runs}
        )
        covered = set()
        for run in runs:
            # segments are (offset-within-request, count) and the run's
            # local ids are contiguous from local_start
            local = run["local_start"]
            for offset, count in run["segments"]:
                for k in range(count):
                    gid = 1 + offset + k
                    assert layout.global_to_local(gid) == (
                        run["shard"],
                        local,
                    )
                    covered.add(gid)
                    local += 1
        assert covered == set(range(1, 1001))

    def test_placement_is_deterministic_across_instances(self):
        a, b = ShardLayout(5, 16), ShardLayout(5, 16)
        assert [a.shard_of_id(g) for g in range(1, 2000)] == [
            b.shard_of_id(g) for g in range(1, 2000)
        ]


class TestShardmapEnv:
    def test_knob_validation(self, monkeypatch):
        monkeypatch.setenv("LO_SHARD_STRIPE_ROWS", "4096")
        monkeypatch.setenv("LO_SHARDMAP_TTL_S", "0")
        shardmap.validate_env()
        assert shardmap.stripe_rows() == 4096
        assert shardmap.map_ttl_s() == 0.0
        for var, bad in [
            ("LO_SHARD_STRIPE_ROWS", "0"),
            ("LO_SHARD_STRIPE_ROWS", "2.5"),
            ("LO_SHARD_STRIPE_ROWS", "lots"),
            ("LO_SHARDMAP_TTL_S", "-1"),
            ("LO_SHARDMAP_TTL_S", "soon"),
        ]:
            monkeypatch.setenv("LO_SHARD_STRIPE_ROWS", "4096")
            monkeypatch.setenv("LO_SHARDMAP_TTL_S", "0")
            monkeypatch.setenv(var, bad)
            with pytest.raises(ValueError):
                shardmap.validate_env()


def _parity_stores(shards=4, stripe=8, rows=1000):
    """A sharded store over InMemoryStores and a plain InMemoryStore
    holding the same content: block rows, a metadata document, and an
    overlay row past the block."""
    plain = InMemoryStore()
    sharded = ShardedStore(
        [InMemoryStore() for _ in range(shards)], stripe_rows=stripe
    )
    rng = np.random.default_rng(7)
    columns = {
        "x": Column.from_numpy(rng.random(rows)),
        "y": Column.from_numpy((np.arange(rows) % 5).astype(np.int64)),
    }
    metadata = {
        ROW_ID: 0,
        "filename": "ds",
        "finished": True,
        "fields": ["x", "y"],
    }
    overlay = {ROW_ID: rows + 10**6, "note": "overlay"}
    for store in (plain, sharded):
        store.create_collection("ds")
        store.insert_one("ds", metadata)
        store.insert_column_arrays("ds", columns, start_id=1)
        store.insert_one("ds", overlay)
    return plain, sharded, rows


def _docs(iterable):
    return [dict(doc) for doc in iterable]


class TestShardedParity:
    def test_reads_and_counts(self):
        plain, sharded, rows = _parity_stores()
        assert sharded.count("ds") == plain.count("ds")
        assert sharded.collection_block_rows("ds") == rows
        for kwargs in (
            {},
            {"start": 100, "limit": 250},
            {"fields": ["x"]},
            {"fields": [ROW_ID, "y"], "start": 7, "limit": 17},
            {"start": rows - 3, "limit": 10},  # crosses into the overlay
        ):
            want = plain.read_column_arrays("ds", **kwargs)
            got = sharded.read_column_arrays("ds", **kwargs)
            assert set(want) == set(got)
            for name in want:
                assert want[name].tolist() == got[name].tolist(), (
                    name,
                    kwargs,
                )

    def test_find_parity(self):
        plain, sharded, rows = _parity_stores()
        queries = [
            {},
            {"y": 3},
            {ROW_ID: 1},
            {ROW_ID: rows},
            {ROW_ID: 0},
            {ROW_ID: rows + 10**6},
            {ROW_ID: rows + 5},  # nobody holds it
            {ROW_ID: {"$lte": 20}},
            {"$or": [{"y": 1}, {"note": "overlay"}]},
        ]
        for query in queries:
            want = _docs(plain.find("ds", query))
            got = _docs(sharded.find("ds", query))
            assert want == got, query
        want = _docs(plain.find("ds", {}, skip=13, limit=9))
        got = _docs(sharded.find("ds", {}, skip=13, limit=9))
        assert want == got

    def test_aggregate_parity(self):
        plain, sharded, rows = _parity_stores()
        pipelines = [
            [{"$group": {"_id": "$y", "count": {"$sum": 1}}}],
            [
                {"$match": {ROW_ID: {"$lte": 50}}},
                {"$group": {"_id": "$y", "count": {"$sum": 1}}},
            ],
            [{"$group": {"_id": f"${ROW_ID}", "count": {"$sum": 1}}}],
        ]
        for pipeline in pipelines:
            want = plain.aggregate("ds", pipeline)
            got = sharded.aggregate("ds", pipeline)
            assert sorted(map(repr, want)) == sorted(map(repr, got)), (
                pipeline
            )

    def test_write_parity(self):
        plain, sharded, rows = _parity_stores()
        for store in (plain, sharded):
            store.set_column(
                "ds",
                "x",
                Column.from_numpy(np.full(10, 4.5)),
                start_id=31,
            )
            store.set_field_values(
                "ds", "y", {3: 99, rows - 1: 98, 0: 97}
            )
            store.update_one(
                "ds", {ROW_ID: 0}, {"finished": False}
            )
        assert (
            plain.read_column_arrays("ds")["x"].tolist()
            == sharded.read_column_arrays("ds")["x"].tolist()
        )
        assert (
            plain.read_column_arrays("ds")["y"].tolist()
            == sharded.read_column_arrays("ds")["y"].tolist()
        )
        assert next(iter(sharded.find("ds", {ROW_ID: 0})))[
            "finished"
        ] is False

    def test_incremental_append_continues_block(self):
        plain, sharded, rows = _parity_stores()
        extra = {
            "x": Column.from_numpy(np.arange(64, dtype=np.float64)),
            "y": Column.from_numpy(np.arange(64, dtype=np.int64)),
        }
        for store in (plain, sharded):
            store.insert_column_arrays("ds2", extra, start_id=1)
            store.insert_column_arrays("ds2", extra)  # start_id=None
        assert sharded.collection_block_rows("ds2") == 128
        assert (
            plain.read_column_arrays("ds2")["x"].tolist()
            == sharded.read_column_arrays("ds2")["x"].tolist()
        )

    def test_shard_signature_and_devcache_token(self):
        from learningorchestra_tpu.core import devcache

        _, sharded, _ = _parity_stores(shards=4, stripe=8)
        assert sharded.shard_signature == "sh4x8"
        assert devcache.store_token(sharded).endswith("sh4x8")
        plain = InMemoryStore()
        assert "sh" not in devcache.store_token(plain)

    def test_fanout_hook_fires(self):
        _, sharded, _ = _parity_stores()
        widths = []
        sharded.on_fanout = widths.append
        sharded.read_column_arrays("ds", start=1, limit=4)
        sharded.insert_column_arrays(
            "ds3",
            {"x": Column.from_numpy(np.arange(100, dtype=np.float64))},
            start_id=1,
        )
        assert widths and all(1 <= w <= 4 for w in widths)


class TestShardScope:
    def test_suffix_only_for_sharded_stores(self):
        sharded = ShardedStore(
            [InMemoryStore() for _ in range(2)], stripe_rows=8192
        )
        assert shard_scope("all", sharded) == "all#sh2x8192"
        # unsharded: byte-identical scope — the degenerate contract
        assert shard_scope("all", InMemoryStore()) == "all"
        assert shard_scope("database_api", object()) == "database_api"


class TestWireSharding:
    """connect()'s `;` grammar against real store servers."""

    def _servers(self, n):
        stores = [InMemoryStore() for _ in range(n)]
        servers = [
            ServerThread(create_store_app(store), "127.0.0.1", 0).start()
            for store in stores
        ]
        urls = [f"http://127.0.0.1:{server.port}" for server in servers]
        return stores, servers, urls

    def test_scatter_gather_over_wire(self, monkeypatch):
        monkeypatch.setenv("LO_SHARD_STRIPE_ROWS", "64")
        stores, servers, urls = self._servers(3)
        store = connect(";".join(urls))
        try:
            assert isinstance(store, ShardedStore)
            rows = 500
            columns = {
                "x": Column.from_numpy(np.arange(rows, dtype=np.float64))
            }
            store.create_collection("ds")
            store.insert_column_arrays("ds", columns, start_id=1)
            assert store.count("ds") == rows
            got = store.read_column_arrays("ds", fields=[ROW_ID, "x"])
            assert got["x"].tolist() == columns["x"].tolist()
            assert got[ROW_ID].tolist() == list(range(1, rows + 1))
            # every group holds a strict subset of the block
            per_group = [s.collection_block_rows("ds") for s in stores]
            assert sum(per_group) == rows
            assert all(0 < n < rows for n in per_group)
            # the shard map landed on the meta group, nowhere else
            assert shardmap.SHARDMAP_COLLECTION in stores[0].list_collections()
            assert store.shardmap_rev() == stores[0].collection_rev(
                shardmap.SHARDMAP_COLLECTION
            )
            # occupancy fans out one dict per group (telemetry feed)
            occupancy = store.shard_occupancy()
            assert len(occupancy) == 3
        finally:
            store.close()
            for server in servers:
                server.stop()

    def test_topology_mismatch_refused(self, monkeypatch):
        monkeypatch.setenv("LO_SHARD_STRIPE_ROWS", "64")
        stores, servers, urls = self._servers(3)
        try:
            store = connect(";".join(urls))
            store.create_collection("ds")
            # a layout-consulting write claims the 3-group map
            store.insert_column_arrays(
                "ds",
                {"x": Column.from_numpy(np.arange(8.0))},
                start_id=1,
            )
            store.close()
            wrong = connect(";".join(urls[:2]))
            with pytest.raises(ValueError, match="topology"):
                wrong.insert_column_arrays(
                    "other",
                    {"x": Column.from_numpy(np.arange(4.0))},
                    start_id=1,
                )
            wrong.close()
        finally:
            for server in servers:
                server.stop()

    def test_degenerate_single_group_is_plain_remote_store(self):
        _, servers, urls = self._servers(1)
        try:
            store = connect(urls[0])
            assert type(store) is RemoteStore
            store.close()
        finally:
            for server in servers:
                server.stop()

    def test_degenerate_wire_traffic_is_byte_identical(self):
        """LO_SHARDS=1/unset golden: the SAME workload through
        ``connect()`` and through a hand-built ``RemoteStore`` produces
        the byte-identical request sequence — sharding must be
        impossible to observe on the wire until a second group exists."""

        def record(app, log):
            def middleware(environ, start_response):
                body = environ["wsgi.input"].read()
                log.append(
                    (
                        environ["REQUEST_METHOD"],
                        environ["PATH_INFO"],
                        environ.get("QUERY_STRING", ""),
                        body,
                    )
                )
                from io import BytesIO

                environ["wsgi.input"] = BytesIO(body)
                environ["CONTENT_LENGTH"] = str(len(body))
                return app(environ, start_response)

            return middleware

        def workload(store):
            store.create_collection("ds")
            store.insert_one("ds", {ROW_ID: 0, "filename": "ds"})
            store.insert_column_arrays(
                "ds",
                {"x": Column.from_numpy(np.arange(32, dtype=np.float64))},
                start_id=1,
            )
            # bounded read: one wire chunk, no speculative read-ahead
            # (the prefetch's request/cancel race would make unbounded
            # reads' traffic timing-dependent on BOTH paths)
            store.read_column_arrays("ds", start=0, limit=32)
            list(store.find("ds", {ROW_ID: 5}))
            store.count("ds")
            store.close()

        logs = []
        for opener in (connect, RemoteStore):
            log = []
            app = record(create_store_app(InMemoryStore()), log)
            server = ServerThread(app, "127.0.0.1", 0).start()
            try:
                workload(opener(f"http://127.0.0.1:{server.port}"))
            finally:
                server.stop()
            logs.append(log)
        assert logs[0] == logs[1]


class TestKillShardPrimaryFast:
    """The kill-one-shard-primary chaos drill, fast in-process variant:
    two replicated shard groups under sync replication; group 1's
    primary dies mid-ingest; its follower is promoted; the
    scatter-gather client rides the group-local takeover and ZERO
    acknowledged writes are lost. Group 0 never notices."""

    def _group(self, sync=True):
        p_port, f_port = _free_port(), _free_port()
        p_url = f"http://127.0.0.1:{p_port}"
        f_url = f"http://127.0.0.1:{f_port}"
        primary = serve(
            "127.0.0.1",
            p_port,
            replicate=True,
            peers=[f_url],
            sync_repl=sync,
            ack_timeout_s=5,
        )
        follower = serve("127.0.0.1", f_port, primary_url=p_url)
        return primary, follower, p_url, f_url

    def test_zero_lost_acked_writes_and_pollers_terminate(
        self, monkeypatch
    ):
        monkeypatch.setenv("LO_REPL_INTERVAL_S", "0.05")
        monkeypatch.setenv("LO_SHARD_STRIPE_ROWS", "16")
        g0 = self._group()
        g1 = self._group()
        store = connect(
            f"{g0[2]},{g0[3]};{g1[2]},{g1[3]}"
        )
        try:
            assert isinstance(store, ShardedStore)
            store.create_collection("ds")
            acked_batches = []
            batch_rows = 64
            for batch in range(4):
                store.insert_column_arrays(
                    "ds",
                    {
                        "x": Column.from_numpy(
                            np.full(batch_rows, float(batch))
                        )
                    },
                    start_id=1 + batch * batch_rows,
                )
                acked_batches.append(batch)

            # wait until every acked record is ON group 1's follower
            # (sync repl guarantees it per ack; belt and braces here),
            # then kill group 1's primary mid-drill and promote
            g1_primary, g1_follower = g1[0], g1[1]
            _wait_for(
                lambda: g1_follower.store.collection_block_rows("ds")
                == g1_primary.store.collection_block_rows("ds"),
                message="group-1 follower sync",
            )
            g1_primary.stop()
            requests.post(f"{g1[3]}/promote", timeout=10)
            _wait_for(
                lambda: g1_follower.store_role.get("writable") is True,
                message="group-1 follower promotion",
            )
            # pollers terminate: the promoted follower's WAL poller is
            # torn down by the takeover
            assert g1_follower.store_role["poller"] is None

            # the client rides the group-local re-point: the next batch
            # lands with no reconfiguration, and every acked row is
            # still present
            store.insert_column_arrays(
                "ds",
                {"x": Column.from_numpy(np.full(batch_rows, 4.0))},
                start_id=1 + 4 * batch_rows,
            )
            acked_batches.append(4)
            got = store.read_column_arrays("ds")["x"].tolist()
            assert len(got) == len(acked_batches) * batch_rows
            for batch in acked_batches:
                chunk = got[batch * batch_rows : (batch + 1) * batch_rows]
                assert chunk == [float(batch)] * batch_rows, (
                    f"acked batch {batch} lost rows"
                )
        finally:
            store.close()
            for group in (g0, g1):
                group[0].stop()
                group[1].stop()


def _spawn(env_extra, *argv):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    env.update(env_extra)
    return subprocess.Popen(
        [sys.executable, *argv],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO_ROOT,
    )


def _wait_line(process, marker, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = process.stdout.readline()
        if not line:
            if process.poll() is not None:
                raise RuntimeError(f"process died (rc={process.returncode})")
            time.sleep(0.05)
            continue
        if marker in line:
            return line.strip()
    raise TimeoutError(f"no {marker!r} line within {timeout}s")


@pytest.mark.slow
@pytest.mark.integration
def test_kill_shard_primary_mid_ingest_subprocess(tmp_path):
    """Slow subprocess variant of the drill: two real WAL-backed shard
    groups, group 1's primary process killed by an armed fault DURING
    an acked mutation, quorum auto-promotion, the scatter-gather client
    riding it — zero lost acknowledged writes end to end."""
    ports = {name: _free_port() for name in (
        "p0", "f0", "a0", "p1", "f1", "a1"
    )}
    url = {name: f"http://127.0.0.1:{port}" for name, port in ports.items()}
    processes = []
    try:
        shared = {
            "LO_REPL_INTERVAL_S": "0.05",
            "LO_STORE_MONITOR_TICK_S": "0.2",
            "LO_SHARD_STRIPE_ROWS": "16",
        }
        for g in (0, 1):
            arbiter = _spawn(
                {"LO_ARBITER_PORT": str(ports[f"a{g}"])},
                "-m",
                "learningorchestra_tpu.core.arbiter",
            )
            processes.append(arbiter)
            _wait_line(arbiter, "store arbiter on ")
            primary_env = {
                **shared,
                "LO_ARBITERS": url[f"a{g}"],
                "LO_STORE_PORT": str(ports[f"p{g}"]),
                "LO_DATA_DIR": str(tmp_path / f"p{g}"),
                "LO_REPLICATE": "1",
                "LO_PEERS": url[f"f{g}"],
                "LO_NODE_ID": f"P{g}",
                "LO_STORE_SYNC_REPL": "1",
                "LO_STORE_ACK_TIMEOUT_S": "5",
            }
            if g == 1:
                # die DURING a mid-burst mutation: applied, never acked
                primary_env["LO_FAULT_STORE_WIRE_MUTATE_APPLIED"] = "kill:4"
            primary = _spawn(
                primary_env, "-m", "learningorchestra_tpu.core.store_service"
            )
            processes.append(primary)
            _wait_line(primary, "store server on ")
            follower = _spawn(
                {
                    **shared,
                    "LO_ARBITERS": url[f"a{g}"],
                    "LO_STORE_PORT": str(ports[f"f{g}"]),
                    "LO_DATA_DIR": str(tmp_path / f"f{g}"),
                    "LO_PRIMARY_URL": url[f"p{g}"],
                    "LO_PEERS": url[f"p{g}"],
                    "LO_NODE_ID": f"F{g}",
                    "LO_AUTO_PROMOTE_S": "1",
                },
                "-m",
                "learningorchestra_tpu.core.store_service",
            )
            processes.append(follower)
            _wait_line(follower, "store server on ")

        os.environ["LO_SHARD_STRIPE_ROWS"] = "16"
        try:
            store = connect(
                f"{url['p0']},{url['f0']};{url['p1']},{url['f1']}"
            )
            assert isinstance(store, ShardedStore)
            store.create_collection("ds")
            batch_rows = 64
            acked = []
            for batch in range(6):
                store.insert_column_arrays(
                    "ds",
                    {
                        "x": Column.from_numpy(
                            np.full(batch_rows, float(batch))
                        )
                    },
                    start_id=1 + batch * batch_rows,
                )
                acked.append(batch)
        finally:
            os.environ.pop("LO_SHARD_STRIPE_ROWS", None)

        # the fault really killed group 1's primary process
        g1_primary = processes[4]
        g1_primary.wait(timeout=30)
        assert g1_primary.returncode == 137

        health = requests.get(f"{url['f1']}/health", timeout=5).json()
        assert health["writable"] is True
        assert health["term"] >= 2

        # zero lost acknowledged writes across BOTH groups
        got = store.read_column_arrays("ds")["x"].tolist()
        assert len(got) == len(acked) * batch_rows
        for batch in acked:
            chunk = got[batch * batch_rows : (batch + 1) * batch_rows]
            assert chunk == [float(batch)] * batch_rows, (
                f"acked batch {batch} lost rows"
            )
        store.close()
    finally:
        for process in processes:
            process.terminate()
        for process in processes:
            try:
                process.wait(timeout=10)
            except subprocess.TimeoutExpired:
                process.kill()
