"""End-to-end telemetry: the metrics registry, Prometheus rendering,
and correlation-ID span propagation REST → job → span tree.

Covers the acceptance surface of the telemetry layer: registry
concurrency, a rendering golden, /metrics on all seven services, the
PhaseTimer→span bridge, the SPMD correlation envelope, and a model
build whose trace phase durations account for the job's wall-clock."""

import threading
import time

import pytest

from learningorchestra_tpu.core.ingest import ingest_csv, write_ingest_metadata
from learningorchestra_tpu.core.jobs import JobManager
from learningorchestra_tpu.ops.dtype import convert_field_types
from learningorchestra_tpu.services import database_api, model_builder
from learningorchestra_tpu.services.runner import build_apps
from learningorchestra_tpu.telemetry import metrics as metrics_mod
from learningorchestra_tpu.telemetry import tracing
from learningorchestra_tpu.telemetry.metrics import MetricsRegistry
from learningorchestra_tpu.utils.profiling import PhaseTimer


class TestRegistry:
    def test_counter_gauge_histogram_concurrency(self):
        registry = MetricsRegistry()
        counter = registry.counter("t_ops_total", "ops", labels=("kind",))
        gauge = registry.gauge("t_depth", "depth")
        hist = registry.histogram("t_secs", "secs", buckets=(0.5, 1.0))
        threads = [
            threading.Thread(
                target=lambda: [
                    (
                        counter.labels("a").inc(),
                        gauge.inc(),
                        hist.observe(0.25),
                    )
                    for _ in range(1000)
                ]
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert counter.value("a") == 8000
        assert gauge.value() == 8000
        text = registry.render()
        assert 't_secs_bucket{le="0.5"} 8000' in text
        assert "t_secs_count 8000" in text

    def test_redeclaration_is_get_or_create(self):
        registry = MetricsRegistry()
        a = registry.counter("t_same", "x", labels=("l",))
        b = registry.counter("t_same", "x", labels=("l",))
        assert a is b
        with pytest.raises(ValueError):
            registry.gauge("t_same", "x", labels=("l",))
        with pytest.raises(ValueError):
            registry.counter("t_same", "x", labels=("other",))

    def test_prometheus_rendering_golden(self):
        registry = MetricsRegistry()
        c = registry.counter("t_req_total", "requests", labels=("svc",))
        c.labels("db").inc(3)
        g = registry.gauge("t_up", "liveness")
        g.set(1)
        h = registry.histogram("t_lat", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        assert registry.render() == (
            "# HELP t_lat latency\n"
            "# TYPE t_lat histogram\n"
            't_lat_bucket{le="0.1"} 1\n'
            't_lat_bucket{le="1"} 1\n'
            't_lat_bucket{le="+Inf"} 2\n'
            "t_lat_sum 5.05\n"
            "t_lat_count 2\n"
            "# HELP t_req_total requests\n"
            "# TYPE t_req_total counter\n"
            't_req_total{svc="db"} 3\n'
            "# HELP t_up liveness\n"
            "# TYPE t_up gauge\n"
            "t_up 1\n"
        )

    def test_label_escaping(self):
        registry = MetricsRegistry()
        c = registry.counter("t_esc", "x", labels=("p",))
        c.labels('a"b\\c\nd').inc()
        assert 't_esc{p="a\\"b\\\\c\\nd"} 1' in registry.render()

    def test_collector_failure_does_not_break_render(self):
        registry = MetricsRegistry()
        registry.gauge("t_ok", "x").set(7)

        def bad(_registry):
            raise RuntimeError("boom")

        registry.register_collector(bad)
        assert "t_ok 7" in registry.render()


class TestTracing:
    def test_span_noop_without_trace(self):
        with tracing.span("orphan") as s:
            assert s is None

    def test_nesting_and_thread_attach(self):
        trace = tracing.Trace("cid01")
        with tracing.activate(trace):
            with tracing.span("outer"):
                with tracing.span("inner"):
                    pass
                context = tracing.capture()

                def worker():
                    with tracing.attach(context), tracing.span("threaded"):
                        pass

                t = threading.Thread(target=worker)
                t.start()
                t.join()
        tree = trace.as_dict()
        assert tree["correlation_id"] == "cid01"
        (outer,) = tree["spans"]
        names = {child["name"] for child in outer["children"]}
        assert names == {"inner", "threaded"}

    def test_phase_timer_bridges_to_spans(self):
        timer = PhaseTimer()
        trace = tracing.Trace("cid02")
        with tracing.activate(trace):
            with timer.phase("fit"):
                time.sleep(0.01)
        assert timer.timings["fit"] > 0
        (span_dict,) = [s.as_dict() for s in trace.spans]
        assert span_dict["name"] == "phase:fit"
        # same clock, same window: the span IS the phase
        assert abs(span_dict["duration_s"] - timer.timings["fit"]) < 0.01

    def test_phase_timer_without_trace_still_times(self):
        timer = PhaseTimer()
        with timer.phase("solo"):
            pass
        assert "solo" in timer.timings


class TestRestSurface:
    def test_metrics_on_all_seven_services(self, store, tmp_path):
        apps = build_apps(store, str(tmp_path / "images"))
        assert len(apps) == 7
        for port, app in apps.items():
            client = app.test_client()
            response = client.get("/metrics")
            assert response.status_code == 200, app.name
            assert response.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            text = response.get_data(as_text=True)
            for family in (
                "lo_http_requests_total",
                "lo_jobs_running",
                "lo_jitcache_persistent_hits",
                "lo_store_collections",
            ):
                assert family in text, (app.name, family)

    def test_request_metrics_and_correlation_header(self, store):
        app = database_api.create_app(store, JobManager())
        client = app.test_client()
        minted = client.get("/files").headers["X-Correlation-Id"]
        assert len(minted) == 16
        echoed = client.get(
            "/files", headers={"X-Correlation-Id": "fixed0123"}
        ).headers["X-Correlation-Id"]
        assert echoed == "fixed0123"
        text = client.get("/metrics").get_data(as_text=True)
        assert (
            'lo_http_requests_total{service="database_api",route="/files",'
            'method="GET",status="200"}'
        ) in text
        assert "lo_http_request_duration_seconds_bucket" in text

    def test_ingest_job_trace_carries_request_correlation_id(
        self, store, titanic_csv
    ):
        jobs = JobManager()
        client = database_api.create_app(store, jobs).test_client()
        response = client.post(
            "/files",
            json={"filename": "titanic", "url": titanic_csv},
            headers={"X-Correlation-Id": "ingest01"},
        )
        assert response.status_code == 201
        jobs.wait("ingest:titanic", timeout=30)
        payload = client.get("/jobs/ingest:titanic/trace").get_json()["result"]
        assert payload["correlation_id"] == "ingest01"
        assert payload["trace"]["correlation_id"] == "ingest01"
        (root,) = payload["trace"]["spans"]
        assert root["name"] == "job:ingest:titanic"
        assert root["duration_s"] > 0
        listing = client.get("/jobs").get_json()["result"]
        assert listing[0]["correlation_id"] == "ingest01"

    def test_unknown_job_trace_404(self, store):
        client = database_api.create_app(store, JobManager()).test_client()
        assert client.get("/jobs/nope/trace").status_code == 404


NUMERIC_FIELDS = (
    "PassengerId", "Survived", "Pclass", "Age", "SibSp", "Parch", "Fare"
)


@pytest.fixture()
def titanic_store(store, titanic_csv):
    for name in ("titanic_train", "titanic_test"):
        write_ingest_metadata(store, name, titanic_csv)
        ingest_csv(store, name, titanic_csv)
        convert_field_types(
            store, name, {f: "number" for f in NUMERIC_FIELDS}
        )
    return store


class TestBuildTrace:
    def test_sync_build_trace_phases_cover_wall_clock(self, titanic_store):
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        jobs = JobManager()
        app = model_builder.create_app(
            titanic_store, models_dir="", jobs=jobs
        )
        client = app.test_client()
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic_train",
                "test_filename": "titanic_test",
                "preprocessor_code": DOCUMENTED_PREPROCESSOR,
                "classificators_list": ["nb"],
            },
            headers={"X-Correlation-Id": "build001"},
        )
        assert response.status_code == 201
        assert response.get_json() == {"result": "created_file"}
        payload = client.get(
            "/jobs/build:titanic_test:nb/trace"
        ).get_json()["result"]
        assert payload["state"] == "finished"
        assert payload["correlation_id"] == "build001"
        (root,) = payload["trace"]["spans"]
        assert root["name"] == "job:build:titanic_test:nb"
        stages = {child["name"]: child for child in root["children"]}
        assert {"load_data", "preprocess", "train:nb"} <= set(stages)
        phases = {
            grandchild["name"]
            for grandchild in stages["train:nb"]["children"]
        }
        assert {"phase:fit", "phase:evaluate", "phase:write"} <= phases
        # acceptance: stage durations sum to within 10% of the job's
        # wall-clock (single classifier — no concurrent-span overlap).
        # abs floor: on a fully warm cache the whole build is ~25 ms and
        # the constant pool-spinup overhead (~3 ms) would exceed 10% of
        # a job that small — the criterion is about minutes-long builds.
        wall = payload["ended_at"] - payload["started_at"]
        covered = sum(child["duration_s"] for child in root["children"])
        assert covered == pytest.approx(wall, rel=0.10, abs=0.05)

    def test_failing_sync_build_runs_once_and_surfaces_error(
        self, titanic_store
    ):
        # run_sync re-raises the build's own ValueError; the handler
        # must not mistake it for "job already active" and rerun the
        # build (the double-execution would duplicate partial writes)
        calls = []

        def exploding_build(body):
            calls.append(1)
            raise ValueError("ragged columns")

        client = model_builder.create_app(
            titanic_store, build=exploding_build, models_dir=""
        ).test_client()
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic_train",
                "test_filename": "titanic_test",
                "preprocessor_code": "",
                "classificators_list": ["nb"],
            },
        )
        assert response.status_code == 500
        assert b"ragged columns" in response.get_data()
        assert calls == [1]

    def test_async_build_gets_same_trace(self, titanic_store):
        from tests.test_frame import DOCUMENTED_PREPROCESSOR

        jobs = JobManager()
        client = model_builder.create_app(
            titanic_store, models_dir="", jobs=jobs
        ).test_client()
        response = client.post(
            "/models",
            json={
                "training_filename": "titanic_train",
                "test_filename": "titanic_test",
                "preprocessor_code": DOCUMENTED_PREPROCESSOR,
                "classificators_list": ["nb"],
                "async": True,
            },
            headers={"X-Correlation-Id": "build002"},
        )
        assert response.status_code == 201
        jobs.wait("build:titanic_test:nb", timeout=120)
        payload = client.get(
            "/jobs/build:titanic_test:nb/trace"
        ).get_json()["result"]
        assert payload["correlation_id"] == "build002"
        (root,) = payload["trace"]["spans"]
        assert any(
            child["name"] == "train:nb" for child in root["children"]
        )


class TestSpmdTelemetry:
    def test_single_process_submit_spans_and_metrics(self):
        from learningorchestra_tpu.parallel.spmd import SpmdDispatcher

        dispatcher = SpmdDispatcher()
        dispatcher.register("noop", lambda payload: payload["x"])
        trace = tracing.Trace("spmd0001")
        with tracing.activate(trace):
            assert dispatcher.submit("noop", {"x": 41}) == 41
        (span_dict,) = [s.as_dict() for s in trace.spans]
        assert span_dict["name"] == "spmd:noop"
        registry = metrics_mod.global_registry()
        assert registry.counter(
            "lo_spmd_jobs_total", "", labels=("op", "outcome")
        ).value("noop", "ok") >= 1

    def test_worker_loop_attributes_broadcast_cid(self, monkeypatch):
        from learningorchestra_tpu.parallel import spmd

        jobs = iter(
            [
                {"op": "work", "payload": {}, "cid": "bcast001"},
                {"op": "__shutdown__"},
            ]
        )
        monkeypatch.setattr(
            spmd, "_broadcast_json", lambda obj=None: next(jobs)
        )
        seen = {}

        def handler(payload):
            seen["cid"] = tracing.current_correlation_id()

        dispatcher = spmd.SpmdDispatcher()
        dispatcher.register("work", handler)
        dispatcher.run_worker_loop()
        # the worker ran under the COORDINATOR's correlation id...
        assert seen["cid"] == "bcast001"
        # ...and parked the finished trace for operator dumps
        remembered = tracing.recall_trace("bcast001")
        (span_dict,) = [s.as_dict() for s in remembered.spans]
        assert span_dict["name"] == "spmd:work"


class TestStoreTelemetry:
    def test_telemetry_stats_shape(self, store):
        store.insert_one("c1", {"a": 1})
        stats = store.telemetry_stats()
        assert stats["collections"] == 1
        assert stats["wal_bytes"] == 0  # pure in-memory store: no WAL
        assert stats["spill_bytes"] == 0

    def test_wal_bytes_reported(self, tmp_path):
        from learningorchestra_tpu.core.store import InMemoryStore

        durable = InMemoryStore(data_dir=str(tmp_path))
        durable.insert_one("c1", {"a": 1})
        assert durable.telemetry_stats()["wal_bytes"] > 0

    def test_resync_apply_reclaims_spill_folders(self, tmp_path):
        from learningorchestra_tpu.core.store import InMemoryStore

        follower = InMemoryStore(replicate=True)
        spill = tmp_path / "spill" / "c1.0"
        spill.mkdir(parents=True)
        (spill / "col.bin").write_bytes(b"x" * 64)
        follower._spill_folders["c1"] = str(spill)
        assert follower.telemetry_stats()["spill_bytes"] == 64
        follower.resync_apply([])
        # the leak: resync cleared collections but stranded the folder
        # mapping and the on-disk files
        assert follower._spill_folders == {}
        assert not spill.exists()
        assert follower.telemetry_stats()["spill_bytes"] == 0
