"""Packaging: `pip install .` must provide the reference client's exact
import surface (reference learning_orchestra_client/setup.py:1-22) —
the "change only the cluster IP" compatibility contract."""

import os
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.integration
def test_pip_install_provides_reference_client_surface(tmp_path):
    target = tmp_path / "site"
    install = subprocess.run(
        [
            sys.executable,
            "-m",
            "pip",
            "install",
            "--quiet",
            "--no-deps",
            "--no-build-isolation",
            "--target",
            str(target),
            _REPO_ROOT,
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert install.returncode == 0, install.stderr

    probe = (
        "from learning_orchestra_client import *\n"
        "Context('127.0.0.1')\n"
        "for cls in (DatabaseApi, Projection, Histogram, Tsne, Pca,"
        " DataTypeHandler, Model):\n"
        "    cls()\n"
        "assert DatabaseApi.DATABASE_API_PORT == '5000'\n"
        "assert Model.MODEL_BUILDER_PORT == '5002'\n"
        "assert callable(Model.predict) and callable(Model.list_models)\n"
        "assert callable(Model.sweep)\n"
        # the fleet lane ships installed: the router-URL probe on the
        # client and the placement/router modules (stdlib imports only
        # at module top — jax/werkzeug load lazily)
        "assert callable(Model._router_base)\n"
        "import learningorchestra_tpu.serve.fleet as fleet\n"
        "assert callable(fleet.validate_env)\n"
        # the coalescing stage + batched-fit entry points ship installed
        "import learningorchestra_tpu.sched.coalesce as co\n"
        "assert callable(co.global_coalescer)\n"
        # the flight recorder ships with the telemetry package (stdlib
        # imports only, so the bare install can load it)
        "import learningorchestra_tpu.telemetry.profile as prof\n"
        "assert callable(prof.chrome_trace)\n"
        "assert callable(prof.sample_stacks)\n"
        # the zero-copy wire (frame v2 + shm ring + dtype policy) ships
        # installed and imports without jax
        "import learningorchestra_tpu.core.shmring as shmring\n"
        "assert callable(shmring.shm_bytes)\n"
        "from learningorchestra_tpu.core.wire import MAGIC_V2\n"
        "from learningorchestra_tpu.utils.dtypepolicy import dtype_policy\n"
        "assert dtype_policy() in ('f32', 'bf16')\n"
        # the event-loop serving core ships installed (stdlib selectors
        # only — the bare install can load it without jax/werkzeug)
        "import learningorchestra_tpu.utils.webloop as webloop\n"
        "assert callable(webloop.validate_env)\n"
        "assert webloop.Waiter and webloop.LoopServer\n"
        "print('client surface ok')\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = str(target)  # ONLY the installed tree
    run = subprocess.run(
        [sys.executable, "-c", probe],
        capture_output=True,
        text=True,
        env=env,
        cwd=str(tmp_path),  # not the repo: imports must resolve from site
        timeout=120,
    )
    assert run.returncode == 0, run.stderr
    assert "client surface ok" in run.stdout
