"""Multi-host runtime: 2 processes x 4 virtual devices == 1 process x 8.

The reference scales by adding Spark workers to its master/worker
overlay (reference: docker-compose.yml:123-163, README.md:94). The TPU
equivalent is jax.distributed over multiple hosts; this test launches a
REAL 2-process runtime (gloo collectives over localhost) on the same
8-device virtual CPU topology the rest of the suite uses, and proves

- the global mesh spans both processes (8 global / 4 local devices);
- a fit on the 2-process mesh produces the same accuracy and (near-)
  identical probabilities as the single-process 8-device fit;
- per-host feeding (`shard_rows_local`) assembles exactly the array the
  single-host `shard_rows` path produces.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from multihost_dataset import make_dataset

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def worker_results(tmp_path_factory):
    outdir = tmp_path_factory.mktemp("multihost")
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
    )
    procs = []
    for pid in range(2):
        out_path = str(outdir / f"p{pid}.json")
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(_TESTS_DIR, "multihost_worker.py"),
                    str(pid),
                    "2",
                    coordinator,
                    out_path,
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                cwd=_TESTS_DIR,
            )
        )
    logs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=420)
        logs.append(out.decode(errors="replace"))
    for pid, (proc, log) in enumerate(zip(procs, logs)):
        assert proc.returncode == 0, f"worker {pid} failed:\n{log}"
    results = []
    for pid in range(2):
        with open(outdir / f"p{pid}.json") as f:
            results.append(json.load(f))
    return results


def test_global_mesh_spans_processes(worker_results):
    for result in worker_results:
        assert result["global_devices"] == 8
        assert result["local_devices"] == 4


def test_processes_agree(worker_results):
    a, b = worker_results
    assert a["accuracy"] == b["accuracy"]
    assert a["predictions"] == b["predictions"]
    np.testing.assert_allclose(a["probs_head"], b["probs_head"], atol=1e-12)


def test_per_host_feeding_matches_global(worker_results):
    # Each host fed only its own contiguous row slice; together they
    # cover [0, n) with no overlap.
    ranges = sorted(tuple(r["host_rows"]) for r in worker_results)
    assert ranges[0][0] == 0
    assert ranges[0][1] == ranges[1][0]
    assert ranges[1][1] == 400
    for result in worker_results:
        assert result["feeding_ok"]


def test_fit_from_per_host_shards(worker_results):
    """fit_sharded on per-host-fed shards reproduces the host-path fit
    (device-side standardization differs only by float32 rounding)."""
    for result in worker_results:
        assert result["sharded_fit_agreement"] >= 0.98


def test_spmd_dispatch_through_store_stack(tmp_path):
    """The multi-host deployment story end to end: a coordinator and a
    worker host share a store server; the coordinator submits a
    build_model job through the SPMD dispatcher (what the model_builder
    REST handler does under LO_COORDINATOR), both processes enter the
    same global-mesh fit, and the store sees exactly one writer."""
    from learningorchestra_tpu.core.ingest import (
        ingest_csv,
        write_ingest_metadata,
    )
    from learningorchestra_tpu.core.store import InMemoryStore, ROW_ID
    from learningorchestra_tpu.core.store_service import (
        RemoteStore,
        create_store_app,
    )
    from learningorchestra_tpu.ops.dtype import convert_field_types
    from learningorchestra_tpu.utils.web import ServerThread

    # Store host may bind 0.0.0.0-free: keep it loopback-only.
    server = ServerThread(
        create_store_app(InMemoryStore()), "127.0.0.1", 0
    ).start()
    try:
        store_url = f"http://127.0.0.1:{server.port}"
        remote = RemoteStore(store_url)
        csv_path = tmp_path / "spmd_train.csv"
        rng = np.random.RandomState(5)
        labels = rng.randint(0, 2, 120)
        with open(csv_path, "w") as f:
            f.write("f1,f2,label\n")
            for lab in labels:
                f.write(
                    f"{lab * 2 + rng.randn():.4f},"
                    f"{-lab + rng.randn():.4f},{lab}\n"
                )
        url = "file://" + str(csv_path)
        write_ingest_metadata(remote, "spmd_train", url)
        ingest_csv(remote, "spmd_train", url)
        convert_field_types(
            remote,
            "spmd_train",
            {"f1": "number", "f2": "number", "label": "number"},
        )

        port = _free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = os.pathsep.join(
            [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
        )
        procs = [
            subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(_TESTS_DIR, "spmd_worker.py"),
                    str(pid),
                    "2",
                    f"127.0.0.1:{port}",
                    store_url,
                    str(tmp_path / "images"),
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                cwd=_TESTS_DIR,
            )
            for pid in range(2)
        ]
        logs = []
        for proc in procs:
            out, _ = proc.communicate(timeout=420)
            logs.append(out.decode(errors="replace"))
        for pid, (proc, log) in enumerate(zip(procs, logs)):
            assert proc.returncode == 0, f"spmd proc {pid} failed:\n{log}"

        # The coordinator (and ONLY the coordinator) wrote predictions.
        name = "spmd_train_prediction_lr"
        assert name in remote.list_collections()
        meta = remote.find_one(name, {"classificator": "lr"})
        assert meta is not None and float(meta["accuracy"]) > 0.8
        rows = remote.count(name)
        assert rows == 121  # 120 predictions + 1 metadata, written once
    finally:
        server.stop()


def test_matches_single_process_fit(worker_results):
    """Mesh invariance across PROCESS topology: 2x4 == 1x8."""
    from learningorchestra_tpu.ml.logistic import LogisticRegression
    from learningorchestra_tpu.parallel.mesh import make_mesh

    X, y = make_dataset()
    mesh = make_mesh()  # conftest pins 8 single-process devices
    model = LogisticRegression(max_iter=25, mesh=mesh).fit(X, y)
    pred = model.predict(X)
    accuracy = float((pred == y).mean())
    probs_head = model.predict_proba(X)[:8]

    for result in worker_results:
        assert result["accuracy"] == accuracy
        np.testing.assert_allclose(
            result["probs_head"], probs_head, atol=1e-6
        )
        agreement = np.mean(np.asarray(result["predictions"]) == pred)
        assert agreement == 1.0


def _run_death_phase(tmp_path, phase: str) -> dict:
    port = _free_port()
    out_path = str(tmp_path / f"{phase}_results.json")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable,
                os.path.join(_TESTS_DIR, "spmd_death.py"),
                str(pid),
                "2",
                f"127.0.0.1:{port}",
                out_path,
                phase,
            ],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            cwd=_TESTS_DIR,
        )
        for pid in range(2)
    ]
    try:
        out, _ = procs[0].communicate(timeout=180)
    finally:
        for proc in procs:  # the drill leaves no clean shutdown behind
            proc.kill()
    assert os.path.exists(out_path), (
        f"coordinator produced no results:\n{out.decode(errors='replace')}"
    )
    with open(out_path) as handle:
        return json.load(handle)


def test_worker_death_fails_cleanly_then_recovers(tmp_path):
    """The fault drill VERDICT r3 asked for: kill a worker mid-fit —
    the coordinator's request must ERROR (watchdog timeout or a
    collective failure), never hang; subsequent jobs fail fast as
    poisoned; and a restarted runtime (the supervisor's job,
    deploy/stack.py) serves the same job successfully."""
    drill = _run_death_phase(tmp_path, "drill")
    assert drill["fit_before"] == 3  # healthy collective: 1 + 2
    assert drill["death_job"] != "no-error", drill
    assert drill["after_death"] in ("poisoned",), drill

    recover = _run_death_phase(tmp_path, "recover")
    assert recover["fit_before"] == 3
